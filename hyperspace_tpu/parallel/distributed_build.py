"""Distributed covering-index build: radix partition + all-to-all bucket
exchange over ICI + per-device sort.

This is the multi-chip version of ops/index_build.py and the TPU-native
equivalent of the reference's repartition(numBuckets, indexedCols) shuffle
(actions/CreateActionBase.scala:118-121; SURVEY §2 distributed primitive 1).
Spark moves rows through its network shuffle service; here every device

  1. bucket-assigns its row shard with the value-stable hash,
  2. radix-groups rows by destination device (contiguous bucket ranges),
  3. exchanges fixed-capacity row blocks with ONE `lax.all_to_all` (ICI),
  4. sorts its received rows by (bucket, indexed columns).

Shapes are static end-to-end: the exchange uses a capacity-bounded buffer
(like MoE dispatch); overflow is detected on device and surfaced as a flag
so the host can retry with a larger capacity factor. Padding rows carry a
validity mask and sort to the tail. The program launches as a
mesh-partitioned ``jax.jit`` through :mod:`.sharding` (NamedSharding +
sharding constraints) and registers in the serving ProgramBank keyed on
(stage fingerprint, shape-class vector, mesh signature).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..exceptions import HyperspaceException
from ..execution.columnar import Column, Table
from ..ops import kernels
from ..schema import STRING
from .mesh import DATA_AXIS, make_mesh
from .sharding import bank_program, device_view


def _bucket_ids_from_arrays(key_arrays: List[jax.Array],
                            key_dtypes: List[str],
                            dict_hash_tables: List[Optional[jax.Array]],
                            num_buckets: int) -> jax.Array:
    h = None
    for arr, dtype, table in zip(key_arrays, key_dtypes, dict_hash_tables):
        if dtype == STRING:
            codes = jnp.clip(arr, 0, table.shape[0] - 1)
            ch = kernels._fmix32(jnp.take(table, codes))
        else:
            ch = kernels.hash32_values(arr, dtype)
        h = ch if h is None else kernels.hash_combine(h, ch)
    return kernels.bucket_ids(h, num_buckets)


def _exchange_and_sort_fn(num_buckets: int, n_dev: int, cap: int,
                          key_names: Tuple[str, ...],
                          key_dtypes: Tuple[str, ...], mesh: Mesh):
    """The full distributed build step as a mesh-partitioned program."""

    def per_device(arrays, valid, dict_hash_tables):
        rows = valid.shape[0]
        key_arrays = [arrays[k] for k in key_names]
        tables = [dict_hash_tables.get(k) for k in key_names]
        bids = _bucket_ids_from_arrays(key_arrays, list(key_dtypes), tables,
                                       num_buckets)
        dst = jnp.minimum((bids.astype(jnp.int32) * n_dev) // num_buckets,
                          n_dev - 1)
        dst = jnp.where(valid, dst, n_dev)  # padding → virtual device n_dev.

        # Radix-group rows by destination device.
        perm = kernels.lex_sort_indices([dst])
        sorted_dst = jnp.take(dst, perm)
        starts = jnp.searchsorted(sorted_dst, jnp.arange(n_dev + 1,
                                                         dtype=sorted_dst.dtype))
        counts = starts[1:] - starts[:-1]
        overflow = jax.lax.pmax(
            jnp.any(counts > cap).astype(jnp.int32), DATA_AXIS)
        pos = jnp.arange(rows, dtype=jnp.int32) - jnp.take(
            starts, jnp.minimum(sorted_dst, n_dev)).astype(jnp.int32)
        slot_ok = (pos < cap) & (sorted_dst < n_dev)
        # Scatter into the fixed [n_dev*cap] send buffer (extra slot drops
        # overflow/padding rows).
        send_idx = jnp.where(slot_ok, sorted_dst * cap + pos, n_dev * cap)

        def scatter(arr):
            taken = jnp.take(arr, perm, axis=0)
            buf = jnp.zeros((n_dev * cap + 1,) + arr.shape[1:], arr.dtype)
            return buf.at[send_idx].set(taken, mode="drop")[:-1]

        send = {name: scatter(a) for name, a in arrays.items()}
        send_valid = jnp.zeros(n_dev * cap + 1, jnp.bool_) \
            .at[send_idx].set(slot_ok, mode="drop")[:-1]

        # ONE all-to-all over ICI: row blocks ride to their bucket owners.
        def a2a(x):
            return jax.lax.all_to_all(
                x.reshape((n_dev, cap) + x.shape[1:]), DATA_AXIS,
                split_axis=0, concat_axis=0).reshape((n_dev * cap,) + x.shape[1:])

        recv = {name: a2a(b) for name, b in send.items()}
        recv_valid = a2a(send_valid)

        # Per-device sort: valid rows first, then (bucket, indexed columns).
        recv_keys = [recv[k] for k in key_names]
        recv_bids = _bucket_ids_from_arrays(recv_keys, list(key_dtypes),
                                            tables, num_buckets)
        sort_ops = [(~recv_valid).astype(jnp.int32), recv_bids] + recv_keys
        perm2 = kernels.lex_sort_indices(sort_ops)
        out = {name: jnp.take(a, perm2, axis=0) for name, a in recv.items()}
        out_valid = jnp.take(recv_valid, perm2)
        out_bids = jnp.where(out_valid, jnp.take(recv_bids, perm2), num_buckets)
        return out, out_valid, out_bids, overflow

    def run(arrays, valid, dict_hash_tables):
        return device_view(
            per_device, mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P()),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()))(
                arrays, valid, dict_hash_tables)

    return run


def _exchange_and_sort(arrays: Dict[str, jax.Array], valid: jax.Array,
                       dict_hash_tables: Dict[str, jax.Array],
                       *, num_buckets: int, n_dev: int, cap: int,
                       key_names: Tuple[str, ...], key_dtypes: Tuple[str, ...],
                       mesh: Mesh):
    global _LAST_PROGRAM
    args = (arrays, valid, dict_hash_tables)
    prog = bank_program(
        "bucket-exchange", mesh,
        (num_buckets, n_dev, cap, key_names, key_dtypes), args,
        lambda: _exchange_and_sort_fn(num_buckets, n_dev, cap, key_names,
                                      key_dtypes, mesh))
    _LAST_PROGRAM = (prog, prog.signature(args))
    return prog(*args)


# (program, shape signature) of the most recent build exchange;
# last_collectives() reads the HLO counts lazily (bench / tests assert
# the exchange is ONE all-to-all class of traffic and zero unrequested
# resharding). The signature is retained, not the live arrays — see
# execution/spmd._LAST_PROGRAM.
_LAST_PROGRAM: Optional[Tuple] = None


def last_collectives() -> Dict[str, int]:
    if _LAST_PROGRAM is None:
        return {}
    prog, sig = _LAST_PROGRAM
    return prog.collectives_for(sig)


# Successful mesh builds in this process (bench/tests assert the
# distributed path actually ran). Bumped only under the lock: builds
# can run concurrently from serving-path actions, and an unguarded +=
# loses updates (HS302, scripts/analysis lock-discipline registry).
DISPATCH_COUNT = 0
_COUNT_LOCK = threading.Lock()

# Cross-process dictionary unions performed (the multihost dryrun asserts
# the string path actually exercised it).
DICT_UNION_COUNT = 0


def _union_string_dictionaries(table: Table) -> Table:
    """Global dictionary union for multi-process builds (VERDICT r5 #8).

    Each process encodes its STRING columns against its own local
    dictionary; shipping those codes through the exchange would let codes
    from different dictionaries meet. Before the exchange, every process
    contributes its dictionaries ONCE host-side (two small allgathers per
    column: sizes, then padded utf-8 blobs — the analogue of Spark
    shipping real strings through its shuffle, paid once per build
    instead of per row), the union is sorted into the one global
    dictionary, and local codes re-encode into it. Single-process runs
    return the table untouched."""
    if jax.process_count() <= 1:
        return table
    if not any(table.column(n).dtype == STRING for n in table.names):
        return table
    global DICT_UNION_COUNT
    from ..cluster import gather as _gather

    new_cols = {}
    for name in table.names:
        col = table.column(name)
        if col.dtype != STRING:
            new_cols[name] = col
            continue
        words = [str(w) for w in col.dictionary.tolist()]
        # Length-prefixed encoding (NOT a sentinel separator: a value may
        # legally contain any byte, and an empty dictionary entry must
        # survive the round trip).
        encoded = [w.encode("utf-8") for w in words]
        lengths = np.array([len(b) for b in encoded], np.int64)
        blob = np.frombuffer(b"".join(encoded), np.uint8) \
            if encoded else np.zeros(0, np.uint8)
        dims = np.asarray(_gather.allgather(
            np.array([len(words), blob.size], np.int64)))
        dims = dims.reshape(-1, 2)
        max_words = max(int(dims[:, 0].max()), 1)
        max_bytes = max(int(dims[:, 1].max()), 1)
        lengths_p = np.zeros(max_words, np.int64)
        lengths_p[:lengths.size] = lengths
        blob_p = np.zeros(max_bytes, np.uint8)
        blob_p[:blob.size] = blob
        all_lengths = np.asarray(_gather.allgather(lengths_p))
        all_blobs = np.asarray(_gather.allgather(blob_p))
        union = set()
        for i in range(dims.shape[0]):
            nw = int(dims[i, 0])
            off = 0
            for ln in all_lengths[i][:nw]:
                ln = int(ln)
                union.add(all_blobs[i][off:off + ln]
                          .tobytes().decode("utf-8"))
                off += ln
        global_dict = np.array(sorted(union), dtype=object)
        remap = np.searchsorted(global_dict, np.array(words, dtype=object)) \
            if words else np.zeros(0, np.int64)
        remap_dev = jnp.asarray(remap.astype(np.int32))
        if remap.size:
            data = jnp.where(col.data >= 0,
                             jnp.take(remap_dev, jnp.maximum(col.data, 0)),
                             col.data)
        else:
            data = col.data
        new_cols[name] = Column(STRING, data, col.validity, global_dict)
    DICT_UNION_COUNT += 1
    return Table(new_cols)


def distributed_build_sorted_buckets(
        table: Table, indexed_cols: Sequence[str], num_buckets: int,
        mesh: Optional[Mesh] = None,
        capacity_factor: float = 2.0,
        process_local_rows: bool = False
        ) -> Tuple[Table, jnp.ndarray, jnp.ndarray]:
    """Distributed hash-partition + sort of ``table`` over ``mesh``.

    Returns (globally sorted-by-(device,bucket,keys) Table, validity mask,
    bucket ids per row) with rows sharded so device i holds exactly the
    buckets in its contiguous range, each sorted by the indexed columns.
    Retries with doubled capacity on exchange overflow (skewed buckets,
    SURVEY §7 hard-part #3).

    ``process_local_rows``: on a multi-process mesh, asserts that
    ``table`` is THIS process's disjoint slice of the source (the
    multihost contract — see pad_and_shard).
    """
    from .mesh import pad_and_shard

    mesh = mesh or make_mesh()
    n_dev = mesh.devices.size
    # Multi-process: string codes must share ONE dictionary before any
    # code crosses the exchange (no-op single-process / no strings).
    table = _union_string_dictionaries(table)
    rows = table.num_rows

    # Column data is shipped under "d:<name>"; a nullable column's validity
    # bitmap rides the same exchange under "v:<name>" (null rows keep their
    # deterministic fill value for hashing/sorting — identical to the
    # single-device build's encoding, so both layouts agree).
    arrays, dict_tables = {}, {}
    key_dtypes = []
    for name in table.names:
        col = table.column(name)
        arrays[f"d:{name}"] = col.data
        if col.validity is not None:
            arrays[f"v:{name}"] = col.validity
        if col.dtype == STRING:
            import zlib
            hashes = np.array([zlib.crc32(s.encode("utf-8"))
                               for s in col.dictionary], dtype=np.uint32) \
                if len(col.dictionary) else np.zeros(1, np.uint32)
            dict_tables[f"d:{name}"] = jnp.asarray(hashes)
    for c in indexed_cols:
        key_dtypes.append(table.column(c).dtype)

    arrays, valid = pad_and_shard(mesh, arrays, rows,
                                  process_local=process_local_rows)
    # Shard size from the GLOBAL padded array, not the local row count:
    # under a multi-process runtime each process holds different local
    # rows, and a locally-derived static capacity would compile different
    # collectives per process (a gloo size-mismatch abort).
    shard_rows = next(iter(arrays.values())).shape[0] // n_dev

    # cap == shard_rows always suffices (a device can send at most its whole
    # shard to one destination), so escalation terminates.
    cap = min(int(shard_rows * capacity_factor / n_dev) + 1, shard_rows)
    while True:
        out, out_valid, out_bids, overflow = _exchange_and_sort(
            arrays, valid, dict_tables,
            num_buckets=num_buckets, n_dev=n_dev, cap=cap,
            key_names=tuple(f"d:{c}" for c in indexed_cols),
            key_dtypes=tuple(key_dtypes), mesh=mesh)
        if not bool(overflow):
            global DISPATCH_COUNT
            with _COUNT_LOCK:
                DISPATCH_COUNT += 1
            out_cols = {}
            for name in table.names:
                src = table.column(name)
                out_cols[name] = Column(src.dtype, out[f"d:{name}"],
                                        out.get(f"v:{name}"), src.dictionary)
            return Table(out_cols), out_valid, out_bids
        if cap >= shard_rows:
            raise HyperspaceException(
                "Bucket exchange overflow at full capacity — this should be "
                "impossible; please report")
        cap = min(cap * 4, shard_rows)
