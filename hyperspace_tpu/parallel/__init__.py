from .distributed_build import distributed_build_sorted_buckets  # noqa: F401
from .distributed_query import (distributed_join_agg,  # noqa: F401
                                distributed_range_agg)
from .mesh import (DATA_AXIS, bucket_owner, device_bucket_range, make_mesh,  # noqa: F401
                   replicated, row_sharding)
