"""Parallel I/O subsystem: pooled ordered file reads + prefetch pipelines.

The reference delegated all I/O parallelism to Spark's task scheduler; this
engine ships its own, and before this module every byte it ingested was read
on one thread. Two primitives fix that, both with a hard determinism
contract (results byte-identical to the sequential loop, any thread count):

- ``map_ordered`` / ``imap_ordered``: fan ``fn(item)`` out over a
  process-wide bounded reader pool, gathering results **in submission
  order** — the per-file-parallel read underneath ``read_parquet``'s
  multi-file fan-out, the sketch builder, and the spill-merge batches.
- ``prefetch_iter``: a producer/consumer pipeline that advances a stream on
  a dedicated thread up to ``prefetchDepth`` items (and ``maxInflightBytes``
  bytes) ahead of the consumer — so chunk k+1 decodes on the host while
  chunk k executes on device (the Flare move, arxiv 1703.08219).

Ordering IS the correctness story: the pool never reorders results, the
prefetcher never reorders the stream, so file→row provenance (lineage ids,
``FileIdTracker`` assignment, dictionary unification) is independent of the
thread count — asserted by tests/test_parallel_io.py at threads
∈ {1, 4, oversubscribed}.

Budgeting: in-flight work is bounded twice — at most ``threads +
prefetchDepth`` results alive at once (the in-flight window plus the one
the consumer holds), and ``maxInflightBytes`` of estimated result bytes
(weights come from file sizes or decoded-table nbytes), so a wide
fan-out over a huge dataset cannot balloon host/device memory. This is
the ONLY module allowed to construct threads (scripts/lint.py gate): an
ad-hoc pool elsewhere would bypass the byte budget.

Nested calls (a pooled task that itself fans out) run sequentially inside
the worker — the classic nested-pool deadlock is impossible by
construction. Conf: ``hyperspace.tpu.io.*`` read via config.py accessors
only; the active session rides a contextvar (``use_session``) set by the
executor and the action framework.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator, Optional

from ..robustness import fault_names as _fn
from ..robustness import faults as _faults
from ..robustness import retry as _retry
from ..telemetry import metrics as _metrics
from ..telemetry import span_names as _sn
from ..telemetry import trace as _trace

# ---------------------------------------------------------------------------
# Parameters (conf-backed; see config.py io_* accessors).
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class IoParams:
    enabled: bool = True
    threads: int = 0  # 0 = auto (min(16, cpu count))
    prefetch_depth: int = 2
    max_inflight_bytes: int = 256 * 1024 * 1024

    def resolved_threads(self) -> int:
        if self.threads > 0:
            return self.threads
        return min(16, max(2, os.cpu_count() or 4))


_DEFAULT_PARAMS = IoParams()

# The session whose conf governs pool parameters AND receives telemetry.
# Set by executor.execute and Action.run (use_session); conf values are
# re-read per call, so runtime conf changes take effect immediately (the
# CacheWithTransform philosophy: knobs are live).
_SESSION: contextvars.ContextVar = contextvars.ContextVar(
    "hst_io_session", default=None)


@contextlib.contextmanager
def use_session(session):
    """Scope the session whose ``hyperspace.tpu.io.*`` conf and event
    logger the io primitives use (None = defaults, no telemetry)."""
    token = _SESSION.set(session)
    try:
        yield
    finally:
        _SESSION.reset(token)


def params_from_conf(hs_conf) -> IoParams:
    """Build IoParams from a HyperspaceConf (validated, clamped sane)."""
    return IoParams(
        enabled=bool(hs_conf.io_enabled()),
        threads=max(int(hs_conf.io_threads()), 0),
        prefetch_depth=max(int(hs_conf.io_prefetch_depth()), 1),
        max_inflight_bytes=max(int(hs_conf.io_max_inflight_bytes()), 1))


def active_params() -> IoParams:
    session = _SESSION.get()
    if session is not None:
        return params_from_conf(session.hs_conf)
    return _DEFAULT_PARAMS


def active_session():
    return _SESSION.get()


# ---------------------------------------------------------------------------
# Process-wide pool.
# ---------------------------------------------------------------------------

_pool_lock = threading.Lock()
_pool: Optional[ThreadPoolExecutor] = None
_pool_size = 0

# Set inside pool tasks: a pooled fn that itself calls map_ordered /
# prefetch_iter runs sequentially (waiting on the pool FROM the pool is
# the textbook thread-starvation deadlock).
_IN_WORKER = threading.local()


def in_worker() -> bool:
    """True on a pool worker thread: nested fan-outs run sequentially
    (deadlock-proof), and readers should stay single-threaded — the pool
    is already the parallelism."""
    return bool(getattr(_IN_WORKER, "flag", False))


def _executor(n: int) -> ThreadPoolExecutor:
    """The shared reader pool, grown (never shrunk) to ``n`` workers.
    Callers that asked for fewer threads are throttled by their submission
    window, not by pool size, so one session's threads=2 does not choke a
    concurrent session's threads=8."""
    global _pool, _pool_size
    with _pool_lock:
        if _pool is None or _pool_size < n:
            old = _pool
            _pool = ThreadPoolExecutor(max_workers=n,
                                       thread_name_prefix="hst-io")
            _pool_size = n
            if old is not None:
                old.shutdown(wait=False)
        return _pool


# ---------------------------------------------------------------------------
# Serving-worker pool (serving/frontend.py drain loops). DISTINCT from the
# reader pool on purpose: a serving worker executes whole queries and must
# be able to fan its reads out underneath (reader-pool workers run nested
# fan-outs sequentially — in_worker()), and a long-running query must not
# occupy a reader slot. Lives here because this module is the lint-
# sanctioned home of every thread construction in the package.
# ---------------------------------------------------------------------------

_serving_lock = threading.Lock()
_serving_pool: Optional[ThreadPoolExecutor] = None
_serving_pool_size = 0


def submit_serving(fn: Callable, threads: int = 4):
    """Run ``fn()`` on the serving-worker pool (grown — never shrunk —
    to ``threads``). Returns the Future. Workers are NOT flagged as
    reader-pool workers, so reads issued inside ``fn`` still
    parallelize."""
    global _serving_pool, _serving_pool_size
    n = max(int(threads), 1)
    with _serving_lock:
        if _serving_pool is None or _serving_pool_size < n:
            old = _serving_pool
            _serving_pool = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="hst-serve")
            _serving_pool_size = n
            if old is not None:
                old.shutdown(wait=False)
        pool = _serving_pool
    while True:
        try:
            return pool.submit(fn)
        except RuntimeError:
            # Pool replaced by a concurrent grow: resubmit on the new one.
            # The SAME pool refusing means it was shut down without
            # replacement (interpreter teardown) — propagate rather than
            # spinning on a dead pool forever.
            with _serving_lock:
                if _serving_pool is pool:
                    raise
                pool = _serving_pool


def spawn_daemon(name: str, fn: Callable) -> threading.Thread:
    """Start ``fn()`` on a named daemon thread and return it. The ONE
    sanctioned long-lived-service spawner (this module is the lint
    gate's only thread-construction site): today it carries the
    telemetry HTTP exporter's accept loop (telemetry/exposition.py) —
    a blocking server loop must not occupy a reader or serving slot,
    and a daemon thread dies with the process, which is exactly the
    lifecycle an observability sidecar wants. ``fn`` must not depend on
    ambient contextvars (nothing propagates here by design)."""
    t = threading.Thread(target=fn, name=name, daemon=True)
    t.start()
    return t


# ---------------------------------------------------------------------------
# Stats (process-wide; explain's "I/O:" section and Hyperspace.io_stats).
# ---------------------------------------------------------------------------

_stats_lock = threading.Lock()
_STATS = {
    "pooled_reads": 0,      # completed map_ordered fan-outs (>1 task)
    "read_tasks": 0,        # individual pooled fn(item) completions
    "read_bytes": 0,        # summed weight estimates of pooled tasks
    "read_seconds": 0.0,    # summed in-worker read+decode time
    "wait_seconds": 0.0,    # consumer time blocked on pool/prefetch results
    "prefetch_streams": 0,  # completed prefetch_iter pipelines
    "prefetch_items": 0,    # items that crossed a prefetch queue
}


def _note(**deltas) -> None:
    with _stats_lock:
        for k, v in deltas.items():
            _STATS[k] += v
    # Per-query attribution: the serving tier's QueryContext (if one is
    # active on this thread/context) gets the same deltas, so io_stats
    # can be charged to the query that caused the reads.
    from ..serving.context import active_context
    ctx = active_context()
    if ctx is not None:
        ctx.note_io(**deltas)


def pool_stats() -> dict:
    """Snapshot of the process-wide pool counters + current sizing."""
    with _stats_lock:
        out = dict(_STATS)
    out["pool_threads"] = _pool_size
    return out


# The pool counters are a named collector in the process metrics
# registry (telemetry/metrics.py): Hyperspace.io_stats() delegates
# through it, and Hyperspace.metrics() snapshots it with every other
# subsystem.
_metrics.get_registry().register_collector("io", pool_stats)


def reset_stats() -> None:
    """Zero the counters (bench A/B phases; never needed for correctness)."""
    with _stats_lock:
        for k in _STATS:
            _STATS[k] = 0 if isinstance(_STATS[k], int) else 0.0


# ---------------------------------------------------------------------------
# Telemetry.
# ---------------------------------------------------------------------------

def _emit(session, event) -> None:
    target = session if session is not None else _SESSION.get()
    if target is None:
        return
    from ..telemetry.logging import get_logger
    try:
        get_logger(target.hs_conf.event_logger_class()).log_event(event)
    except Exception:
        # Telemetry must never fail a read (a misconfigured logger class
        # already raises loudly on the action path).
        pass


def _emit_read(session, label: str, files: int, nbytes: int,
               seconds: float, threads: int) -> None:
    from ..telemetry.events import IoReadEvent
    _emit(session, IoReadEvent(
        message=f"pooled read '{label}': {files} file task(s)",
        files=files, nbytes=nbytes, seconds=round(seconds, 4),
        threads=threads))


def _emit_wait(session, label: str, wait_seconds: float,
               read_seconds: float, items: int) -> None:
    from ..telemetry.events import IoWaitEvent
    _emit(session, IoWaitEvent(
        message=f"prefetch stream '{label}': {items} item(s)",
        where=label, wait_seconds=round(wait_seconds, 4),
        read_seconds=round(read_seconds, 4), items=items))


# ---------------------------------------------------------------------------
# Ordered pooled map.
# ---------------------------------------------------------------------------

def imap_ordered(fn: Callable, items: Iterable, *,
                 weight: Optional[Callable] = None,
                 params: Optional[IoParams] = None,
                 label: str = "read", session=None) -> Iterator:
    """Yield ``fn(item)`` for every item IN ORDER, fanning the calls out
    over the shared pool with a bounded window and in-flight byte budget.

    ``weight(item)`` estimates the bytes a result will hold (file size,
    spill-batch size); submission pauses while the estimated in-flight
    bytes exceed ``maxInflightBytes`` (the first pending task is always
    allowed, so an over-budget single item still makes progress).

    Residency bound: at most ``threads + prefetchDepth`` results are
    ALIVE at once — the in-flight window plus the one the consumer
    holds. The window refills just before each yield, so the next read
    overlaps the consumer's work even at the minimum window of one
    (threads=2, depth=0 — the chunked build's strict double buffer).

    Sequential (plain loop, no pool) when the pool is disabled, threads
    <= 1, a single item, or when called from inside a pool worker.
    """
    items = list(items)
    p = params if params is not None else active_params()
    n = p.resolved_threads()
    # Robustness captures, taken CONSUMER-side (pool workers never see
    # the contextvars): the armed fault registry, the retry policy of
    # the governing session, and whether the active query carries a
    # deadline. All three are no-ops in the default configuration.
    reg = _faults.armed()
    sess = session if session is not None else _SESSION.get()
    pol = _retry.policy_from_conf(sess.hs_conf) if sess is not None \
        else _retry.DEFAULT_POLICY

    def _read(it):
        # The retried pooled-read body: the fault point sits INSIDE so
        # injected transient faults exercise the real retry path; the
        # ordered gather makes attempt-2 results byte-identical to
        # attempt-1 results by construction (reads are idempotent).
        def _attempt():
            _faults.fault_point(_fn.IO_POOLED_READ, reg=reg)
            return fn(it)

        return _retry.call(_attempt, where="io.pooled_read",
                           policy=pol, session=sess)

    if not p.enabled or n <= 1 or len(items) <= 1 or in_worker():
        # Sequential path: process-wide pool counters deliberately stay
        # untouched (they count POOLED work), but the serving tier's
        # per-query attribution still wants these reads charged.
        from ..serving.context import active_context
        ctx = active_context()
        if ctx is not None and items:
            w = sum(int(weight(it)) for it in items) \
                if weight is not None else 0
            ctx.note_io(read_tasks=len(items), read_bytes=w)
        for it in items:
            yield _read(it)
        return

    def _task(it):
        _IN_WORKER.flag = True
        t0 = time.perf_counter()
        return _read(it), time.perf_counter() - t0

    ex = _executor(n)

    def _submit(it):
        # The pool can be REPLACED under us by a concurrent stream that
        # asked for more threads (grow-only _executor); the old pool still
        # runs everything already submitted, but rejects new work — grab
        # the replacement and continue (looped: another stream may race
        # a further replacement in between).
        nonlocal ex
        while True:
            try:
                return ex.submit(_task, it)
            except RuntimeError:
                ex = _executor(n)

    window = max(n + max(p.prefetch_depth, 0) - 1, 1)
    budget = p.max_inflight_bytes
    pending: deque = deque()
    state = {"inflight": 0}
    done = 0
    read_s = 0.0
    wait_s = 0.0
    nbytes = 0
    i = 0
    t_start = time.perf_counter()

    def _refill():
        nonlocal i
        while i < len(items) and len(pending) < window:
            w = int(weight(items[i])) if weight is not None else 0
            if pending and state["inflight"] + w > budget:
                break
            pending.append((_submit(items[i]), w))
            state["inflight"] += w
            i += 1

    from ..serving.context import check_deadline, deadline_remaining_s
    from concurrent.futures import TimeoutError as _FutTimeout
    has_deadline = deadline_remaining_s() is not None
    try:
        _refill()
        while pending:
            fut, w = pending.popleft()
            t0 = time.perf_counter()
            if has_deadline:
                # Cooperative cancellation in the consumer-wait loop: a
                # deadline'd query polls instead of blocking forever on
                # a wedged read (the finally below cancels the window).
                while True:
                    check_deadline("io.read")
                    try:
                        result, task_s = fut.result(timeout=0.05)
                        break
                    except _FutTimeout:
                        if fut.done():
                            # Either the task completed in the race
                            # window after the wait timed out, or the
                            # TASK itself raised TimeoutError (on 3.11+
                            # futures.TimeoutError IS the builtin).
                            # Re-resolving the done future yields the
                            # real result or the task's real error —
                            # never the wait timeout, and never a
                            # masked spin until the deadline.
                            result, task_s = fut.result()
                            break
                        continue
            else:
                result, task_s = fut.result()
            wait_s += time.perf_counter() - t0
            state["inflight"] -= w
            done += 1
            read_s += task_s
            nbytes += w
            # Refill BEFORE yielding: the next reads run while the
            # consumer processes this result.
            _refill()
            yield result
    finally:
        for fut, _ in pending:
            fut.cancel()
        _note(pooled_reads=1, read_tasks=done, read_bytes=nbytes,
              read_seconds=read_s, wait_seconds=wait_s)
        # Trace attribution rides the same consumer-side seam as _note's
        # per-query io counters: pool workers never see the query's
        # context, the consuming thread does.
        _trace.add_span(_sn.IO_READ, start_perf=t_start, label=label,
                        files=done, nbytes=nbytes,
                        read_seconds=round(read_s, 4),
                        wait_seconds=round(wait_s, 4), threads=n)
        _emit_read(session, label, done, nbytes, read_s, n)


def map_ordered(fn: Callable, items: Iterable, *,
                weight: Optional[Callable] = None,
                params: Optional[IoParams] = None,
                label: str = "read", session=None) -> list:
    """``list(imap_ordered(...))`` — the eager form for callers that need
    every result anyway (read_parquet's multi-file fan-out)."""
    return list(imap_ordered(fn, items, weight=weight, params=params,
                             label=label, session=session))


# ---------------------------------------------------------------------------
# Producer/consumer prefetch pipeline.
# ---------------------------------------------------------------------------

_DONE = object()


def prefetch_iter(source: Iterable, *,
                  nbytes: Optional[Callable] = None,
                  params: Optional[IoParams] = None,
                  label: str = "prefetch", session=None) -> Iterator:
    """Iterate ``source`` with a dedicated producer thread running up to
    ``prefetchDepth`` items (and ``maxInflightBytes`` estimated bytes)
    ahead of the consumer — chunk k+1 reads/decodes while chunk k is being
    consumed (executed on device). Order, values, and exceptions are
    exactly the source's own; an abandoned consumer (early break) stops
    and closes the producer.

    The producer runs under a copy of the caller's context, so
    contextvar-scoped state (shape-class params, the executing session)
    behaves as if the source ran inline. Pass-through (no thread) when
    the pool is disabled, threads <= 1, or inside a pool worker.
    """
    p = params if params is not None else active_params()
    if not p.enabled or p.resolved_threads() <= 1 or in_worker():
        yield from source
        return

    depth = max(p.prefetch_depth, 1)
    budget = p.max_inflight_bytes
    cond = threading.Condition()
    buf: deque = deque()
    state = {"bytes": 0, "closed": False, "read_s": 0.0, "error": None}

    def _room() -> bool:
        return len(buf) < depth and (not buf or state["bytes"] < budget)

    def _produce():
        it = iter(source)
        try:
            while True:
                # Wait for room BEFORE advancing the source: producing
                # first would hold one extra decoded item outside the
                # queue, silently raising the residency bound the depth
                # and byte budget promise (at most depth buffered + one
                # at the consumer + one in production).
                with cond:
                    while not _room() and not state["closed"]:
                        cond.wait()
                    if state["closed"]:
                        break
                t0 = time.perf_counter()
                try:
                    # The producer runs under a COPY of the consumer's
                    # context, so the armed fault registry (and the
                    # query's io attribution) propagate here by the same
                    # mechanism — an injected error crosses the queue
                    # and surfaces typed at the consumer below.
                    _faults.fault_point(_fn.IO_PREFETCH_PRODUCE)
                    item = next(it)
                except StopIteration:
                    break
                state["read_s"] += time.perf_counter() - t0
                w = int(nbytes(item)) if nbytes is not None else 0
                with cond:
                    if state["closed"]:
                        break
                    buf.append((item, w))
                    state["bytes"] += w
                    cond.notify_all()
        except BaseException as e:  # re-raised at the consumer
            with cond:
                state["error"] = e
                cond.notify_all()
        finally:
            if hasattr(it, "close"):
                try:
                    it.close()
                except Exception:
                    pass
            with cond:
                buf.append((_DONE, 0))
                cond.notify_all()

    ctx = contextvars.copy_context()
    producer = threading.Thread(target=ctx.run, args=(_produce,),
                                name=f"hst-io-prefetch-{label}", daemon=True)
    producer.start()
    wait_s = 0.0
    items = 0
    t_start = time.perf_counter()
    from ..serving.context import check_deadline, deadline_remaining_s
    has_deadline = deadline_remaining_s() is not None
    try:
        while True:
            t0 = time.perf_counter()
            with cond:
                while not buf and state["error"] is None:
                    # Deadline'd queries poll the consumer wait so a
                    # stalled producer cannot outlive the cancellation
                    # (the finally below closes the producer).
                    cond.wait(0.05 if has_deadline else None)
                    if has_deadline:
                        check_deadline("io.prefetch")
                if state["error"] is not None and not buf:
                    raise state["error"]
                item, w = buf.popleft()
                state["bytes"] -= w
                cond.notify_all()
            wait_s += time.perf_counter() - t0
            if item is _DONE:
                if state["error"] is not None:
                    raise state["error"]
                break
            items += 1
            yield item
    finally:
        with cond:
            state["closed"] = True
            buf.clear()
            state["bytes"] = 0
            cond.notify_all()
        producer.join(timeout=30.0)
        _note(prefetch_streams=1, prefetch_items=items,
              wait_seconds=wait_s, read_seconds=state["read_s"])
        _trace.add_span(_sn.IO_PREFETCH, start_perf=t_start, label=label,
                        items=items,
                        read_seconds=round(state["read_s"], 4),
                        wait_seconds=round(wait_s, 4))
        _emit_wait(session, label, wait_s, state["read_s"], items)


def zip_prefetch(items, fn: Callable, **kwargs) -> Iterator:
    """(item, fn(item)) pairs in order, reads pooled ahead of the consumer
    — the per-file pipeline shape (sketch builds: reads fan out while the
    consumer computes device reductions file by file)."""
    items = list(items)
    return zip(items, imap_ordered(fn, items, **kwargs))
