"""Multi-host (DCN) scale-out for the distributed backend.

The reference reaches multi-node scale through Spark's cluster manager +
shuffle service; the TPU-native equivalent is JAX's multi-process runtime:
every host runs the same program, `jax.distributed.initialize` wires the
processes over DCN, and `jax.devices()` then spans every chip in the slice
— at which point the SAME collectives this framework already uses
(lax.all_to_all bucket exchanges in parallel/distributed_build.py and
execution/spmd.py, psum/pmin/pmax aggregation) ride ICI within a host and
DCN across hosts: `make_mesh()` simply sees more devices. The caller-side
contract that changes is the INPUT: each process must feed its own
disjoint slice of the source (pad_and_shard's ``process_local`` flag);
paths that read the full dataset in every process fail loudly rather
than silently duplicating rows.

Single-host processes (and the CI's virtual CPU mesh) skip initialization
entirely, so the framework is identical from one chip to a pod slice.

This is NOT an init-helper-only contract: the distributed build really
executes across a process boundary in CI — __graft_entry__.dryrun_multihost
forms a 2-process × N-device jax.distributed cluster on CPU (gloo
collectives standing in for DCN), each process contributes its own local
rows (mesh._pad_and_shard_multihost assembles the global row-sharded
arrays from per-process blocks, padding to the worldwide max shard so
every process compiles identical collectives), and the bucket exchange
crosses processes with row conservation, host-hash bucket agreement, and
single ownership verified (tests/test_multihost.py).

STRING columns build across processes through a global dictionary union
(distributed_build._union_string_dictionaries): before the exchange,
every process contributes its local dictionaries host-side (two small
allgathers per column), the sorted union becomes the one shared
dictionary, and local codes re-encode into it — so the exchange only
ever moves codes from a single code space. The dryrun pins both the
numeric path and a string indexed column with per-process-disjoint
value sets (__graft_entry__.dryrun_multihost).
"""

from __future__ import annotations

import os
from typing import Optional

from .mesh import make_mesh

# Coordinator address of the cluster this process joined (or ""), kept
# for cluster/gather.py's host-TCP rendezvous key — the one place the
# fleet already shares an identity, so no extra env contract is needed.
_COORDINATOR = ""


def last_coordinator_address() -> str:
    """The coordinator address ``initialize_multihost`` joined with, ""
    for single-process runs."""
    return _COORDINATOR


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> dict:
    """Join this process to a multi-host JAX runtime (idempotent; no-op for
    single-process runs).

    Arguments default to the standard env vars (JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID — also set by TPU pod launchers),
    matching how the reference defers cluster wiring to the launcher.
    Returns a summary dict {initialized, process_index, process_count,
    local_devices, global_devices}.
    """
    import jax

    coordinator = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    n_proc = num_processes if num_processes is not None else int(
        os.environ.get("JAX_NUM_PROCESSES", "0") or 0)
    if coordinator and n_proc <= 1:
        # Half-configured multi-host is a loud error: silently running
        # single-host would compute over a fraction of the data.
        raise ValueError(
            "Coordinator address set but num_processes <= 1; set "
            "JAX_NUM_PROCESSES (and JAX_PROCESS_ID) on every host")
    initialized = False
    if coordinator and n_proc > 1:
        pid = process_id if process_id is not None else int(
            os.environ.get("JAX_PROCESS_ID", "0") or 0)
        already = getattr(jax.distributed, "is_initialized", lambda: False)()
        if not already:
            # The CPU backend refuses multiprocess collectives unless the
            # gloo implementation is selected BEFORE initialize; on
            # builds without the knob (or non-CPU platforms) the failure
            # is harmless and cluster/gather.py's host-TCP path still
            # covers the host-side allgathers.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=n_proc,
                    process_id=pid)
            except RuntimeError as e:
                # A second initialize (another Session in this process)
                # must be a no-op, per the idempotency contract.
                if "already initialized" not in str(e):
                    raise
        global _COORDINATOR
        _COORDINATOR = str(coordinator)
        initialized = True
    return {
        "initialized": initialized,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def global_mesh():
    """The data mesh over EVERY device in the (possibly multi-host) runtime.
    Collectives partition automatically: ICI legs within a host, DCN legs
    across hosts (XLA inserts the hierarchy)."""
    return make_mesh()
