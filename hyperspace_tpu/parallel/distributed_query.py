"""Distributed query execution over the device mesh.

The consumer side of the distributed build (SURVEY §2 distributed
primitives 5–6): queries run SPMD over row shards with XLA collectives —
``psum`` over ICI — instead of a network shuffle. Both entry points launch
mesh-partitioned ``jax.jit`` programs through :mod:`.sharding` (NamedSharding
+ sharding constraints; see that module for the launcher contract):

- ``distributed_range_agg``: filter (range predicate) + aggregate in one
  mesh program; each device masks its shard and contributes partial
  sums/counts, one psum returns replicated scalars (the TPC-H Q6 shape).
- ``distributed_join_agg``: inner equi-join + aggregate over two tables
  bucket-co-partitioned by the SAME key hash (e.g. two
  distributed_build_sorted_buckets outputs): equal keys live on the same
  device on both sides, so each device merge-joins locally (searchsorted
  over its re-sorted shard, prefix-sum segment totals) and a single psum
  combines — the shuffle-free sort-merge-join aggregate (the Q3/Q17 inner
  shape) with zero row movement. ``join_agg_collectives`` exposes the
  compiled program's HLO collective counts so tests/bench can ASSERT the
  zero-resharding property instead of trusting it.

All shapes are static; join results are aggregated on device (count, left-
and right-value sums) rather than materialized, so no variable-length
output crosses the program boundary.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..exceptions import HyperspaceException
from ..execution.columnar import Table
from .mesh import DATA_AXIS, make_mesh, pad_and_shard
from .sharding import bank_program, device_view


def _range_agg_fn(mesh: Mesh, value_names: Tuple[str, ...], lo_incl: bool,
                  hi_incl: bool):
    def per_device(fd, v, lo, hi, vals):
        ml = (fd >= lo) if lo_incl else (fd > lo)
        mh = (fd <= hi) if hi_incl else (fd < hi)
        m = ml & mh & v
        count = jax.lax.psum(jnp.sum(m.astype(jnp.int64)), DATA_AXIS)
        sums = {name: jax.lax.psum(
            jnp.sum(jnp.where(m, vals[name], 0)), DATA_AXIS)
            for name in value_names}
        return count, sums

    def run(filter_data, valid, lo, hi, values):
        return device_view(
            per_device, mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P(), P(DATA_AXIS)),
            out_specs=(P(), P()))(filter_data, valid, lo, hi, values)

    return run


def _range_agg(filter_data, valid, lo, hi, values, *, mesh: Mesh,
               value_names: Tuple[str, ...], lo_incl: bool, hi_incl: bool):
    args = (filter_data, valid, lo, hi, values)
    prog = bank_program(
        "range-agg", mesh, (value_names, lo_incl, hi_incl), args,
        lambda: _range_agg_fn(mesh, value_names, lo_incl, hi_incl))
    return prog(*args)


def distributed_range_agg(table: Table, filter_col: str, lo, hi,
                          value_cols: Tuple[str, ...] = (),
                          mesh: Optional[Mesh] = None,
                          lo_incl: bool = True, hi_incl: bool = True):
    """count + per-column sums of rows with ``lo <(=) filter_col <(=) hi``,
    computed SPMD over the mesh. Returns (count, {col: sum})."""
    mesh = mesh or make_mesh()
    fcol = table.column(filter_col)
    if fcol.validity is not None:
        raise HyperspaceException("distributed_range_agg: nullable filter "
                                  "column not supported yet")
    arrays = {"__f": fcol.data}
    for c in value_cols:
        col = table.column(c)
        if col.validity is not None:
            raise HyperspaceException(
                f"distributed_range_agg: nullable value column '{c}' not "
                "supported yet")
        arrays[c] = col.data
    sharded, valid = pad_and_shard(mesh, arrays, table.num_rows)
    fd = sharded.pop("__f")
    lo_a = jnp.asarray(lo, fd.dtype)
    hi_a = jnp.asarray(hi, fd.dtype)
    count, sums = _range_agg(fd, valid, lo_a, hi_a, sharded, mesh=mesh,
                             value_names=tuple(value_cols),
                             lo_incl=lo_incl, hi_incl=hi_incl)
    return int(count), {k: v for k, v in sums.items()}


def _merge_join_agg_body(lk, lvalid, lval, rk, rvalid, rval):
    """The per-device co-bucketed merge-join aggregate, shared by the
    plain join-aggregate program and the fused join+filter+aggregate
    region (which pre-folds its consumer filter into ``lvalid``).

    Local re-sort of the right shard by pure key (device-local order
    after the bucket exchange is (bucket, key); searchsorted needs key
    order). Invalid rows get the max-value sentinel and, via the
    valid-first tiebreak, sort strictly after every valid row — so
    valid rows occupy [0, n_valid) and clamping the probe bounds to
    n_valid keeps a legitimate sentinel-valued key from matching the
    padding (no overcount even for key == iinfo.max)."""
    from ..ops import kernels

    if jnp.issubdtype(rk.dtype, jnp.floating):
        sentinel = jnp.asarray(jnp.finfo(rk.dtype).max, rk.dtype)
    else:
        sentinel = jnp.asarray(jnp.iinfo(rk.dtype).max, rk.dtype)
    rk_eff = jnp.where(rvalid, rk, sentinel)
    order = kernels.lex_sort_indices(
        [rk_eff, (~rvalid).astype(jnp.int32)])
    n_valid = jnp.sum(rvalid.astype(jnp.int32))
    rk_sorted = jnp.take(rk_eff, order)
    rval_sorted = jnp.where(jnp.take(rvalid, order),
                            jnp.take(rval, order), 0)
    prefix = jnp.concatenate(
        [jnp.zeros(1, rval_sorted.dtype), jnp.cumsum(rval_sorted)])

    lo = jnp.minimum(jnp.searchsorted(rk_sorted, lk, side="left"),
                     n_valid)
    hi = jnp.minimum(jnp.searchsorted(rk_sorted, lk, side="right"),
                     n_valid)
    counts = jnp.where(lvalid, (hi - lo).astype(jnp.int64), 0)
    pair_count = jax.lax.psum(jnp.sum(counts), DATA_AXIS)
    # Sum of left values over all join pairs: multiplicity × value.
    left_sum = jax.lax.psum(
        jnp.sum(counts.astype(lval.dtype) * jnp.where(lvalid, lval, 0)),
        DATA_AXIS)
    # Sum of right values over all join pairs: per-left segment totals.
    seg = jnp.take(prefix, hi) - jnp.take(prefix, lo)
    right_sum = jax.lax.psum(jnp.sum(jnp.where(lvalid, seg, 0)),
                             DATA_AXIS)
    return pair_count, left_sum, right_sum


def _join_agg_fn(mesh: Mesh):
    def per_device(lk, lvalid, lval, rk, rvalid, rval):
        return _merge_join_agg_body(lk, lvalid, lval, rk, rvalid, rval)

    def run(lk, lv_valid, lval, rk, rv_valid, rval):
        return device_view(
            per_device, mesh,
            in_specs=(P(DATA_AXIS),) * 6,
            out_specs=(P(), P(), P()))(lk, lv_valid, lval, rk, rv_valid,
                                       rval)

    return run


def _join_agg_program(args, mesh: Mesh):
    return bank_program("join-agg", mesh, (), args,
                        lambda: _join_agg_fn(mesh))


def join_agg_collectives(left: Table, left_valid, right: Table, right_valid,
                         key: str, left_value: str, right_value: str,
                         mesh: Optional[Mesh] = None) -> Dict[str, int]:
    """HLO collective counts of the co-bucketed join-aggregate program for
    these inputs (compiling it if cold). The shuffle-free property the
    build's co-partitioning buys is exactly: zero all-to-all / all-gather /
    collective-permute / reduce-scatter — only the final psum all-reduces.
    Tests and the bench assert on this."""
    mesh = mesh or make_mesh()
    args = (left.column(key).data, left_valid, left.column(left_value).data,
            right.column(key).data, right_valid,
            right.column(right_value).data)
    return _join_agg_program(args, mesh).collectives(*args)


def _join_region_fn(mesh: Mesh, lo_incl: bool, hi_incl: bool):
    """The FUSED co-bucketed join REGION: the shuffle-free sort-merge
    join composed with its consumers — a post-join range filter on a
    stream column and the aggregate — in ONE partitioned executable (the
    whole-plan-fusion contract of execution/fusion.py, extended to the
    distributed tier). Staged execution would dispatch one program for
    the filter and another for the join-aggregate; here the filter folds
    into the stream mask BEFORE the local merge, so the composition
    still moves zero rows between devices (zero all-to-all/all-gather —
    asserted on compiled HLO by join_filter_agg_collectives) and
    launches exactly one program."""

    def per_device(lk, lvalid, lval, fd, flo, fhi, rk, rvalid, rval):
        ml = (fd >= flo) if lo_incl else (fd > flo)
        mh = (fd <= fhi) if hi_incl else (fd < fhi)
        # The fused consumer filter folds into the stream validity BEFORE
        # the shared merge body — everything else is the same program.
        return _merge_join_agg_body(lk, lvalid & ml & mh, lval,
                                    rk, rvalid, rval)

    def run(lk, lv_valid, lval, fd, flo, fhi, rk, rv_valid, rval):
        return device_view(
            per_device, mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS), P(), P(),
                      P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(), P(), P()))(lk, lv_valid, lval, fd, flo, fhi,
                                       rk, rv_valid, rval)

    return run


def _join_region_args(left: Table, left_valid, right: Table, right_valid,
                      key: str, left_value: str, right_value: str,
                      filter_col: str, lo, hi):
    fd = left.column(filter_col).data
    return (left.column(key).data, left_valid,
            left.column(left_value).data, fd,
            jnp.asarray(lo, fd.dtype), jnp.asarray(hi, fd.dtype),
            right.column(key).data, right_valid,
            right.column(right_value).data)


def _join_region_program(args, mesh: Mesh, lo_incl: bool, hi_incl: bool):
    return bank_program(
        "join-filter-agg", mesh, (lo_incl, hi_incl), args,
        lambda: _join_region_fn(mesh, lo_incl, hi_incl))


def join_filter_agg_collectives(left: Table, left_valid, right: Table,
                                right_valid, key: str, left_value: str,
                                right_value: str, filter_col: str, lo, hi,
                                mesh: Optional[Mesh] = None,
                                lo_incl: bool = True,
                                hi_incl: bool = True) -> Dict[str, int]:
    """HLO collective counts of the fused join+filter+aggregate region.
    The acceptance property: composing the consumer into the
    co-bucketed join keeps zero all-to-all / all-gather /
    collective-permute / reduce-scatter — only the final psums
    all-reduce."""
    mesh = mesh or make_mesh()
    args = _join_region_args(left, left_valid, right, right_valid, key,
                             left_value, right_value, filter_col, lo, hi)
    return _join_region_program(args, mesh, lo_incl,
                                hi_incl).collectives(*args)


def distributed_join_filter_agg(left: Table, left_valid, right: Table,
                                right_valid, key: str, left_value: str,
                                right_value: str, filter_col: str, lo, hi,
                                mesh: Optional[Mesh] = None,
                                lo_incl: bool = True, hi_incl: bool = True):
    """Inner-join aggregate over two bucket-co-partitioned sharded tables
    with a FUSED post-join range filter on ``filter_col`` (a stream-side
    column): one partitioned executable, zero inter-device row movement.
    Returns (pair count, sum(left_value), sum(right_value)) over join
    pairs whose stream row passes ``lo <(=) filter_col <(=) hi``."""
    mesh = mesh or make_mesh()
    for t, cols in ((left, (key, left_value, filter_col)),
                    (right, (key, right_value))):
        for c in cols:
            if t.column(c).validity is not None:
                raise HyperspaceException(
                    f"distributed_join_filter_agg: nullable column '{c}' "
                    "not supported yet (SQL null-key semantics)")
    args = _join_region_args(left, left_valid, right, right_valid, key,
                             left_value, right_value, filter_col, lo, hi)
    count, lsum, rsum = _join_region_program(args, mesh, lo_incl,
                                             hi_incl)(*args)
    return int(count), np.asarray(lsum).item(), np.asarray(rsum).item()


def distributed_join_agg(left: Table, left_valid, right: Table, right_valid,
                         key: str, left_value: str, right_value: str,
                         mesh: Optional[Mesh] = None):
    """Inner-join aggregate over two bucket-co-partitioned sharded tables
    (outputs of distributed_build_sorted_buckets over the same mesh and
    bucket count, keyed on ``key``): returns

        (pair count, sum(left_value over pairs), sum(right_value over pairs))

    with zero inter-device row movement — co-partitioning makes every join
    match device-local; one psum combines the partial aggregates."""
    mesh = mesh or make_mesh()
    for t, cols in ((left, (key, left_value)), (right, (key, right_value))):
        for c in cols:
            if t.column(c).validity is not None:
                raise HyperspaceException(
                    f"distributed_join_agg: nullable column '{c}' not "
                    "supported yet (SQL null-key semantics)")
    lk = left.column(key).data
    rk = right.column(key).data
    lval = left.column(left_value).data
    rval = right.column(right_value).data
    args = (lk, left_valid, lval, rk, right_valid, rval)
    count, lsum, rsum = _join_agg_program(args, mesh)(*args)
    return int(count), np.asarray(lsum).item(), np.asarray(rsum).item()
