"""Device mesh helpers.

The framework's scaling axis is sharded columnar buckets across cores
(SURVEY §5 long-context note): a 1-D mesh over the data axis ``d``. Buckets
are assigned to devices in contiguous ranges, so the bucket exchange is a
single all-to-all over ICI and the per-device output is already grouped for
the bucketed parquet write.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "d"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (DATA_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def device_bucket_range(device_index: int, n_devices: int,
                        num_buckets: int) -> tuple:
    """Contiguous bucket range [lo, hi) owned by a device."""
    lo = (device_index * num_buckets) // n_devices
    hi = ((device_index + 1) * num_buckets) // n_devices
    return lo, hi


def bucket_owner(bucket_ids, n_devices: int, num_buckets: int):
    """Device index owning each bucket id (inverse of device_bucket_range)."""
    import jax.numpy as jnp
    return jnp.minimum((bucket_ids * n_devices) // num_buckets, n_devices - 1)
