"""Device mesh helpers.

The framework's scaling axis is sharded columnar buckets across cores
(SURVEY §5 long-context note): a 1-D mesh over the data axis ``d``. Buckets
are assigned to devices in contiguous ranges, so the bucket exchange is a
single all-to-all over ICI and the per-device output is already grouped for
the bucketed parquet write.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "d"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (DATA_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_and_shard(mesh: Mesh, arrays: dict, rows: int,
                  process_local: bool = False,
                  pad_rows: Optional[int] = None) -> tuple:
    """Zero-pad each 1-D-leading array to a device multiple, build the
    validity mask, and device_put everything row-sharded over the data axis.
    Returns (sharded arrays dict, sharded valid mask). The single shared
    recipe for putting host rows onto the mesh (build + query sides).

    ``pad_rows``: optional padding target ≥ ``rows`` (the r07 length
    class) — callers that want repeated executions of different-length
    inputs to share ONE compiled mesh program pad to the class instead
    of the exact device multiple; the valid mask keeps results
    byte-identical either way.

    When ``mesh`` spans multiple processes (jax.distributed over DCN) the
    caller must state what its rows ARE: ``process_local=True`` means
    ``arrays`` hold THIS process's disjoint slice of the data — every
    process pads its block to the worldwide max local shard (one
    allgather of row counts) and the global row-sharded arrays assemble
    from the per-process blocks. Callers that read the FULL dataset in
    every process (the current query paths) must NOT silently shard it —
    that would duplicate every row — so they fail loudly instead until
    reader sharding exists."""
    import jax.numpy as jnp

    spans = {d.process_index for d in mesh.devices.flat}
    if len(spans) > 1:
        if not process_local:
            raise NotImplementedError(
                "pad_and_shard over a multi-process mesh needs "
                "process-local input rows (process_local=True); sharding "
                "a full-dataset copy from every process would duplicate "
                "rows. Multi-process reads currently require the caller "
                "to split the source per process (see parallel/multihost "
                "and __graft_entry__.dryrun_multihost).")
        return _pad_and_shard_multihost(mesh, arrays, rows)
    n_dev = mesh.devices.size
    # Arrays may arrive ALREADY class-padded beyond ``rows`` (the r07
    # padded pipeline hands its tables to the SPMD boundary untrimmed —
    # compacting would compile one gather per distinct valid count);
    # the shard target covers the largest physical length so padding
    # only ever grows.
    cur_max = max((int(a.shape[0]) for a in arrays.values()), default=0)
    target = max(rows, pad_rows or 0, cur_max, 1)
    shard = -(-target // n_dev)  # ceil.
    padded = shard * n_dev
    out = {}
    for name, a in arrays.items():
        cur = int(a.shape[0])
        if padded != cur:
            a = jnp.concatenate(
                [a, jnp.zeros((padded - cur,) + a.shape[1:], a.dtype)])
        out[name] = a
    # Host-built mask: a jnp.concatenate here would compile one tiny
    # program per distinct valid count — the exact storm class padding
    # exists to avoid.
    vm = np.zeros(padded, bool)
    vm[:rows] = True
    sharding = row_sharding(mesh)
    return ({n: jax.device_put(a, sharding) for n, a in out.items()},
            jax.device_put(jnp.asarray(vm), sharding))


def pad_and_shard_blocks(mesh: Mesh, arrays: dict, bounds,
                         shard_rows: Optional[int] = None) -> tuple:
    """File-aligned sharding: ``bounds`` (``n_dev + 1`` ascending row
    offsets) assigns contiguous row blocks — whole files, as computed by
    the caller from parquet metadata — to devices. Each block pads to the
    largest block so every shard is equal (static shapes); the validity
    mask marks each block's real rows. Results are byte-identical to the
    even split (row order is preserved and padding is masked), but each
    device's rows come from its OWN files — the layout a multi-process
    pod needs to read only its shard's files host-side, and the layout
    that keeps per-shard host reads contiguous in the reader pool.

    ``shard_rows``: optional per-device shard size ≥ the largest block
    (the r07 length class of it) so different file layouts share one
    compiled program."""
    import jax.numpy as jnp

    n_dev = mesh.devices.size
    if len(bounds) != n_dev + 1:
        raise ValueError("bounds must have n_dev + 1 offsets")
    sizes = [int(bounds[i + 1]) - int(bounds[i]) for i in range(n_dev)]
    shard = max(max(sizes), shard_rows or 0, 1)
    sharding = row_sharding(mesh)

    def assemble(a):
        # One slice + pad per block, one concatenate: O(padded) copies.
        # (Chained buf.at[...].set() updates would copy the FULL padded
        # buffer once per device — O(n_dev * padded) — and a host-side
        # numpy buffer would force a device->host round trip per column
        # on real accelerators.)
        parts = []
        for i in range(n_dev):
            blk = a[int(bounds[i]):int(bounds[i + 1])]
            if sizes[i] < shard:
                blk = jnp.concatenate(
                    [blk, jnp.zeros((shard - sizes[i],) + a.shape[1:],
                                    a.dtype)])
            parts.append(blk)
        return jax.device_put(jnp.concatenate(parts), sharding)

    out = {n: assemble(a) for n, a in arrays.items()}
    vm = np.zeros(shard * n_dev, bool)
    for i in range(n_dev):
        vm[i * shard:i * shard + sizes[i]] = True
    return out, jax.device_put(jnp.asarray(vm), sharding)


def _pad_and_shard_multihost(mesh: Mesh, arrays: dict, rows: int) -> tuple:
    """Multi-process assembly: local rows → global row-sharded arrays.
    The per-device shard is sized to the LARGEST process block so every
    device shard is equal (static shapes worldwide); short processes pad
    with invalid rows."""
    from ..cluster import gather as _gather

    n_total = mesh.devices.size
    n_local = len(mesh.local_devices)
    # One allgather carries (rows, n_local): asymmetric device counts
    # would compile different collectives per process — the gloo
    # size-mismatch abort — so fail loudly up front instead. The
    # cluster gather seam picks the transport (native collective when
    # the backend has one, the owned host-TCP star when it doesn't).
    stats = np.asarray(_gather.allgather(
        np.array([rows, n_local], np.int64)))
    if n_local == 0 or not (stats[..., 1] == n_local).all():
        raise NotImplementedError(
            "multi-process pad_and_shard requires every process to hold "
            f"the same number of mesh-local devices; saw "
            f"{stats[..., 1].tolist()}")
    shard = -(-max(int(stats[..., 0].max()), 1) // n_local)  # worldwide max
    local_padded = shard * n_local
    global_rows = shard * n_total
    sharding = row_sharding(mesh)

    def assemble(a):
        a = np.asarray(a)
        if local_padded != a.shape[0]:
            pad = np.zeros((local_padded - a.shape[0],) + a.shape[1:],
                           a.dtype)
            a = np.concatenate([a, pad])
        return jax.make_array_from_process_local_data(
            sharding, a, (global_rows,) + a.shape[1:])

    out = {n: assemble(a) for n, a in arrays.items()}
    valid = assemble(np.concatenate(
        [np.ones(rows, bool), np.zeros(local_padded - rows, bool)]))
    return out, valid


def device_bucket_range(device_index: int, n_devices: int,
                        num_buckets: int) -> tuple:
    """Contiguous bucket range [lo, hi) owned by a device."""
    lo = (device_index * num_buckets) // n_devices
    hi = ((device_index + 1) * num_buckets) // n_devices
    return lo, hi


def bucket_owner(bucket_ids, n_devices: int, num_buckets: int):
    """Device index owning each bucket id (inverse of device_bucket_range)."""
    import jax.numpy as jnp
    return jnp.minimum((bucket_ids * n_devices) // num_buckets, n_devices - 1)
