"""Device mesh helpers.

The framework's scaling axis is sharded columnar buckets across cores
(SURVEY §5 long-context note): a 1-D mesh over the data axis ``d``. Buckets
are assigned to devices in contiguous ranges, so the bucket exchange is a
single all-to-all over ICI and the per-device output is already grouped for
the bucketed parquet write.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "d"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (DATA_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_and_shard(mesh: Mesh, arrays: dict, rows: int) -> tuple:
    """Zero-pad each 1-D-leading array to a device multiple, build the
    validity mask, and device_put everything row-sharded over the data axis.
    Returns (sharded arrays dict, sharded valid mask). The single shared
    recipe for putting host rows onto the mesh (build + query sides)."""
    import jax.numpy as jnp

    n_dev = mesh.devices.size
    shard = -(-max(rows, 1) // n_dev)  # ceil.
    padded = shard * n_dev
    out = {}
    for name, a in arrays.items():
        if padded != rows:
            a = jnp.concatenate(
                [a, jnp.zeros((padded - rows,) + a.shape[1:], a.dtype)])
        out[name] = a
    valid = jnp.concatenate([jnp.ones(rows, jnp.bool_),
                             jnp.zeros(padded - rows, jnp.bool_)])
    sharding = row_sharding(mesh)
    return ({n: jax.device_put(a, sharding) for n, a in out.items()},
            jax.device_put(valid, sharding))


def device_bucket_range(device_index: int, n_devices: int,
                        num_buckets: int) -> tuple:
    """Contiguous bucket range [lo, hi) owned by a device."""
    lo = (device_index * num_buckets) // n_devices
    hi = ((device_index + 1) * num_buckets) // n_devices
    return lo, hi


def bucket_owner(bucket_ids, n_devices: int, num_buckets: int):
    """Device index owning each bucket id (inverse of device_bucket_range)."""
    import jax.numpy as jnp
    return jnp.minimum((bucket_ids * n_devices) // num_buckets, n_devices - 1)
