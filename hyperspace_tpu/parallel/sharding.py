"""Global-view SPMD launcher: ``NamedSharding`` + ``jax.jit`` over an
explicit :class:`Mesh`.

Every distributed path (execution/spmd.py, parallel/distributed_build.py,
parallel/distributed_query.py) writes its program as a *per-device*
function — static shapes, a validity mask riding along, ``lax`` collectives
(psum/pmin/pmax/all_to_all/all_gather) over the mesh axis name. Earlier
revisions launched those bodies with a per-device mapping primitive; this
module launches them in the partitioned-jit idiom instead, which is the
form that composes with the serving tier's program bank and scales to
multi-process TPU pods (pjit partitions inputs across all devices, and
pre-partitioned handoffs between jitted stages avoid resharding):

- :func:`device_view` reshapes each row-sharded global array from
  ``(n_dev * shard, ...)`` to ``(n_dev, shard, ...)`` — a zero-exchange
  resharding, every device's rows stay put — pins the layout with
  ``with_sharding_constraint`` (``PartitionSpec(axis, None, ...)``), and
  runs the per-device body under ``jax.vmap(..., axis_name=axis)``. jax's
  collective batching rules give ``lax.psum``/``all_to_all``/… over the
  vmapped axis exactly their per-device semantics, and because the batch
  axis is mesh-sharded, GSPMD lowers them to the real ICI collectives.
  The per-device program bodies did not change in the port — only the
  launcher did.

- :class:`MeshProgram` is the AOT wrapper the call sites register in the
  serving tier's ProgramBank: one entry per (stage fingerprint, mesh
  signature), holding one compiled executable per argument shape
  signature. Owning the compile step (``lower().compile()``) is what
  makes the compiled-HLO collective counts observable — the
  ``ShardedExecutionEvent`` / zero-resharding assertions read them from here
  — without ever paying a second compile on the dispatch path.

Replication contract: an ``out_specs`` entry of ``P()`` asserts the
per-device value is identical on every device (it is the result of a
psum/pmax-style collective); the launcher materializes device 0's copy.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# HLO collective categories counted from compiled programs. "all-to-all"
# is the bucket exchange; "all-reduce" the psum/pmin/pmax partial merges;
# "all-gather"/"collective-permute"/"reduce-scatter" indicate resharding
# the program did NOT ask for (the shuffle-free join asserts these are 0).
COLLECTIVE_OPS = ("all-to-all", "all-reduce", "all-gather",
                  "collective-permute", "reduce-scatter")

# Mesh programs compiled in this process (bench/tests read this alongside
# the r07 backend-compile counter; one MeshProgram compile == one entry).
COMPILE_COUNT = 0

# Mesh program DISPATCHES in this process (one per MeshProgram.__call__):
# the fused-region assertions count executable launches — a composed
# join+consumer region must dispatch ONE partitioned program where the
# staged composition dispatched several.
DISPATCH_COUNT = 0

# Tests assert these tallies EXACTLY, and concurrent serving workers
# bump them; a per-instance lock (or none) loses increments under
# contention, so both counters move only under this module lock
# (HS302, scripts/analysis lock-discipline registry).
_COUNT_LOCK = threading.Lock()


def mesh_signature(mesh: Mesh) -> tuple:
    """Hashable identity of a mesh for program keys and telemetry:
    (axis names, device grid shape, platform). Two meshes with the same
    signature compile identical partitioned programs."""
    return (tuple(mesh.axis_names), tuple(mesh.devices.shape),
            str(mesh.devices.flat[0].platform))


def collective_counts(hlo_text: str) -> Dict[str, int]:
    """Occurrences of each collective op in compiled HLO text. Only the
    opcode position counts — ``op(`` — not the ``%op``-style instruction
    names or operand references that repeat it on the same line.
    Start/done pairs (async collectives) count once via the ``-start``
    form when present."""
    counts = {}
    for op in COLLECTIVE_OPS:
        starts = len(re.findall(rf"\b{op}-start\(", hlo_text))
        plain = len(re.findall(rf"\b{op}\(", hlo_text))
        counts[op] = starts if starts else plain
    return counts


def _is_sharded(spec: P) -> bool:
    return len(spec) > 0 and spec[0] is not None


def _leading_spec(mesh: Mesh, x) -> NamedSharding:
    axis = mesh.axis_names[0]
    return NamedSharding(mesh, P(axis, *([None] * (max(x.ndim, 1) - 1))))


def _prefix_apply(specs, tree, fn):
    """Apply ``fn(spec, subtree)`` treating ``specs`` as a pytree prefix of
    ``tree`` with PartitionSpec leaves (the in_specs/out_specs convention:
    one spec may cover a whole dict of arrays)."""
    spec_leaves, spec_treedef = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda s: isinstance(s, P))
    subtrees = spec_treedef.flatten_up_to(tree)
    mapped = [fn(s, t) for s, t in zip(spec_leaves, subtrees)]
    return jax.tree_util.tree_unflatten(spec_treedef, mapped)


def device_view(fn: Callable, mesh: Mesh, in_specs, out_specs) -> Callable:
    """Run a per-device SPMD body in the global partitioned-jit view.

    ``fn`` sees per-device shards (leading row axis = its shard) and may
    use lax collectives over the mesh axis name. Call inside ``jax.jit``;
    sharding is pinned with ``with_sharding_constraint`` so GSPMD emits
    the collectives the body asked for and nothing else.
    """
    n_dev = mesh.devices.size
    axis = mesh.axis_names[0]

    def run(*args):
        in_axes = []
        split_args = []
        for arg, spec in zip(args, in_specs):
            if _is_sharded(spec):
                def split(x):
                    x = jax.lax.with_sharding_constraint(
                        x, _leading_spec(mesh, x))
                    return x.reshape(
                        (n_dev, x.shape[0] // n_dev) + x.shape[1:])
                split_args.append(jax.tree_util.tree_map(split, arg))
                in_axes.append(0)
            else:
                split_args.append(jax.tree_util.tree_map(
                    lambda x: jax.lax.with_sharding_constraint(
                        x, NamedSharding(mesh, P())), arg))
                in_axes.append(None)

        out = jax.vmap(fn, in_axes=tuple(in_axes), out_axes=0,
                       axis_name=axis)(*split_args)

        def finish(spec, subtree):
            if _is_sharded(spec):
                def unsplit(x):
                    x = x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
                    return jax.lax.with_sharding_constraint(
                        x, _leading_spec(mesh, x))
                return jax.tree_util.tree_map(unsplit, subtree)
            # Replicated: collective-reduced, identical across devices —
            # materialize device 0's copy (see module docstring).
            return jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(
                    x[0], NamedSharding(mesh, P())), subtree)

        return _prefix_apply(out_specs, out, finish)

    return run


class MeshProgram:
    """One SPMD stage, AOT-compiled per argument shape signature.

    ``fn`` is a plain (unjitted) function of the dynamic arguments; static
    configuration must already be bound (partial/closure). Each distinct
    (shape, dtype, weak_type) signature lowers and compiles exactly once;
    the compiled executable and its HLO collective counts are retained.
    """

    def __init__(self, fn: Callable, name: str = "spmd",
                 artifact_key: tuple = None):
        self._fn = fn
        self._name = name
        # The bank key ("spmd", name, static fingerprint, mesh
        # signature) when registered via bank_program — the identity
        # the artifact store persists executables under. None = no
        # persistence (ad-hoc MeshPrograms in tests).
        self._artifact_key = artifact_key
        self._lock = threading.Lock()
        # shape signature -> [compiled, collective counts or None,
        # artifact digest or None, loaded-from-artifact flag].
        # Counts are computed LAZILY on the first collectives() ask:
        # compiled.as_text() renders multi-MB HLO for wide meshes, and
        # paying that on the dispatch path would tax every cold query
        # for an observability detail most dispatches never read.
        self._compiled: Dict[tuple, list] = {}

    @staticmethod
    def _sig(args) -> tuple:
        def leaf(x):
            aval = jax.api_util.shaped_abstractify(x)
            return (aval.shape, str(aval.dtype),
                    bool(getattr(aval, "weak_type", False)))
        leaves, treedef = jax.tree_util.tree_flatten(args)
        return (treedef, tuple(leaf(x) for x in leaves))

    def _artifact_seam(self, sig):
        """(manager, key fields, digest) when the ACTIVE session
        persists artifacts and this program carries a bank key;
        (None, None, None) otherwise — including on any artifacts-layer
        trouble, which must never cost an SPMD dispatch."""
        if self._artifact_key is None:
            return None, None, None
        try:
            from ..artifacts.manager import active_manager
            from ..artifacts.store import key_digest, key_fields
            mgr = active_manager()
            if mgr is None:
                return None, None, None
            mesh_sig = self._artifact_key[3] \
                if len(self._artifact_key) > 3 else ""
            fields = key_fields("spmd", repr(self._artifact_key),
                                repr(sig), mesh_repr=repr(mesh_sig))
            return mgr, fields, key_digest(fields)
        except Exception:
            return None, None, None

    def _get(self, args) -> list:
        sig = self._sig(args)
        entry = self._compiled.get(sig)
        if entry is not None:
            return entry
        with self._lock:
            entry = self._compiled.get(sig)
            if entry is None:
                global COMPILE_COUNT
                from ..robustness import fault_names as _fn
                from ..robustness import faults as _faults
                from ..telemetry import span_names as _sn
                from ..telemetry import trace as _tr
                # Artifact store probe (r20): a lake hit skips the
                # compile entirely — COMPILE_COUNT stays flat, which is
                # the cold-boot acceptance signal.
                mgr, fields, digest = self._artifact_seam(sig)
                if mgr is not None:
                    compiled = mgr.fetch(fields)
                    if compiled is not None:
                        entry = [compiled, None, digest, True]
                        self._compiled[sig] = entry
                        return entry
                # Robustness fault point: an injected compile failure
                # propagates to the dispatch site, where the executor's
                # SPMD->single-device degradation ladder absorbs it.
                _faults.fault_point(_fn.SPMD_COMPILE)
                with _tr.span(_sn.SPMD_COMPILE, stage=self._name):
                    # shardings: inferred from the committed NamedSharding
                    # inputs; device_view pins every internal layout with
                    # with_sharding_constraint (see module docstring).
                    compiled = jax.jit(self._fn).lower(*args).compile()
                entry = [compiled, None, digest, False]
                self._compiled[sig] = entry
                with _COUNT_LOCK:
                    COMPILE_COUNT += 1
                if mgr is not None:
                    mgr.put(fields, compiled)
        return entry

    def __call__(self, *args):
        global DISPATCH_COUNT
        with _COUNT_LOCK:
            DISPATCH_COUNT += 1
        entry = self._get(args)
        try:
            out = entry[0](*args)
        except Exception:
            if not entry[3]:
                raise
            # A lake-loaded executable failed at dispatch — the corrupt
            # ladder's last rung: evict it everywhere, compile fresh,
            # answer exactly.
            self._evict_artifact(args, entry[2])
            entry = self._get(args)
            out = entry[0](*args)
        if entry[2] is not None:
            self._note_use(entry[2])
        return out

    def _evict_artifact(self, args, digest) -> None:
        sig = self._sig(args)
        with self._lock:
            self._compiled.pop(sig, None)
        try:
            from ..artifacts.manager import active_manager
            mgr = active_manager()
            if mgr is not None and digest is not None:
                mgr.discard(digest)
        except Exception:
            pass  # eviction is best-effort; the recompile is the fix

    @staticmethod
    def _note_use(digest: str) -> None:
        """Per-dispatch usage tally (the preload ordering input)."""
        try:
            from ..artifacts.manager import active_manager
            mgr = active_manager()
            if mgr is not None:
                mgr.note_use(digest)
        except Exception:
            pass  # tallies are advisory

    def signature(self, args) -> tuple:
        """The shape signature of an argument tuple — retain THIS (not
        the live arguments) to read a dispatched program's collectives
        later: holding device arrays would pin the query's whole sharded
        input in device memory after the dispatch returns."""
        return self._sig(args)

    def collectives(self, *args) -> Dict[str, int]:
        """Collective counts of the program compiled for these argument
        shapes (compiling it if never run). Counted from the compiled
        HLO once per program, then cached."""
        return self._counts(self._get(args))

    def collectives_for(self, sig: tuple) -> Dict[str, int]:
        """Collective counts of the already-compiled program for this
        :meth:`signature`; ``{}`` if no such program was ever compiled
        (never compiles — the reader path must not pay or mask one)."""
        entry = self._compiled.get(sig)
        return {} if entry is None else self._counts(entry)

    def _counts(self, entry: list) -> Dict[str, int]:
        if entry[1] is None:
            with self._lock:
                if entry[1] is None:
                    entry[1] = collective_counts(entry[0].as_text())
        return dict(entry[1])

    @property
    def programs(self) -> int:
        return len(self._compiled)


def shape_vector(args) -> tuple:
    """The bank's shape-class vector for an argument tuple: one
    (shape, dtype) pair per array leaf. SPMD inputs are already padded to
    static shapes (pad_and_shard / the r07 padding contract), so this is
    the shape-class identity of the executable about to run."""
    return tuple((tuple(x.shape), str(x.dtype))
                 for x in jax.tree_util.tree_leaves(args))


def bank_program(name: str, mesh: Mesh, static_key: tuple, args: tuple,
                 build: Callable[[], Callable]) -> MeshProgram:
    """Fetch (or create) the :class:`MeshProgram` for an SPMD stage from
    the process-wide serving ProgramBank.

    The bank key is (stage name, static fingerprint, mesh signature) —
    the r11 registry extended with the mesh identity, so two sessions on
    the same mesh share every sharded executable while a resized mesh
    compiles its own. The argument shape signature rides as the bank's
    shape-class vector (hit/miss accounting + events)."""
    from ..serving.program_bank import get_bank
    key = ("spmd", name, static_key, mesh_signature(mesh))
    return get_bank().lookup(key, shape_vector(args),
                             lambda: MeshProgram(build(), name,
                                                 artifact_key=key))
