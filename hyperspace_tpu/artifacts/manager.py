"""Process-wide artifact managers + the AOT dispatch wrapper.

The seam between the banked interfaces and the lake store (store.py):

- :func:`maybe_wrap_stage` — ProgramBank registration hook. When the
  active query's session enables artifacts, newly registered jit-
  wrapper stages are wrapped in an :class:`AotStage`, which AOT-
  compiles per argument signature (``lower().compile()``), imports/
  exports through the store, and falls back to the inner jit wrapper on
  ANY trouble. When artifacts are off nothing is wrapped — the off
  path is byte-identical by construction (tests assert it).
- :class:`ArtifactManager` — one per store root: the load-through
  cache of deserialized executables (what preload populates, what the
  dispatch seams probe before compiling) plus the preload driver.
- ``MeshProgram`` (parallel/sharding.py) talks to the SAME manager from
  its ``_get`` compile seam; the artifact key travels from
  ``bank_program``.

Importable without jax (config.py reaches the constants package; the
bank imports this module on the serving path): jax only loads inside
the dispatch/compile functions.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

from .constants import ARTIFACT_DIR_NAME
from .store import ArtifactStore, key_digest, key_fields

# Sentinel for signatures whose AOT path failed (un-lowerable args, a
# rejected loaded executable): dispatch goes to the inner jit wrapper,
# permanently for that signature.
_FALLBACK = ("__aot_fallback__",)


def _signature(args) -> tuple:
    """(treedef, per-leaf (shape, dtype, weak_type)) — the same
    signature MeshProgram keys executables on; its repr feeds the
    artifact key's ``sig`` digest."""
    import jax

    def leaf(x):
        aval = jax.api_util.shaped_abstractify(x)
        return (aval.shape, str(aval.dtype),
                bool(getattr(aval, "weak_type", False)))
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (treedef, tuple(leaf(x) for x in leaves))


class ArtifactManager:
    """Load-through executable cache over one :class:`ArtifactStore`.
    ``_loaded`` (digest -> compiled) is shared by every dispatch seam
    and the boot preloader — all access under ``_lock`` (HS301)."""

    def __init__(self, store: ArtifactStore):
        self.store = store
        self._lock = threading.Lock()
        self._loaded: Dict[str, object] = {}
        self.warm_hits = 0
        self.preloaded = 0
        self.preload_ms = 0.0
        self.preload_bytes = 0
        # Utility-kernel executables ((label, statics, signature) ->
        # (compiled, digest) | _FALLBACK) under their own lock:
        # _acquire_kernel holds it across a fetch/put, which takes
        # ``_lock`` — the ordering is always _util_lock -> _lock.
        self._util_lock = threading.Lock()
        self._util: Dict[tuple, Tuple] = {}

    def fetch(self, fields: Dict[str, str]):
        """The compiled executable for this key — from the in-memory
        cache (preloaded or previously loaded) or the lake — else None
        (the caller compiles)."""
        digest = key_digest(fields)
        with self._lock:
            compiled = self._loaded.get(digest)
            if compiled is not None:
                self.warm_hits += 1
                return compiled
        compiled = self.store.load(fields)
        if compiled is not None:
            with self._lock:
                self._loaded[digest] = compiled
        return compiled

    def put(self, fields: Dict[str, str], compiled) -> None:
        """Publish a freshly compiled executable (best-effort; losing a
        publication race or failing to serialize costs persistence
        only) and retain it for sibling stages in this process."""
        self.store.publish(fields, compiled)
        with self._lock:
            self._loaded[key_digest(fields)] = compiled

    def note_use(self, digest: str) -> None:
        self.store.record_use(digest)

    def discard(self, digest: str) -> None:
        """Last rung of the corrupt ladder: a loaded executable failed
        at dispatch — drop it from memory and the lake so no process
        loads it again."""
        with self._lock:
            self._loaded.pop(digest, None)
        try:
            os.unlink(self.store.blob_path(digest))
        except OSError:
            pass

    def preload(self, max_ms: float, max_bytes: int) -> dict:
        """Load resident blobs hottest-first (persisted usage order)
        until either budget is spent — the boot warm-up that makes a
        second process reach first-query with compile count ~ 0."""
        from ..telemetry import span_names as SN
        from ..telemetry import trace as _trace
        t0 = time.perf_counter()
        loaded = skipped = 0
        nbytes_total = 0
        budget_hit = ""
        with _trace.span(SN.ARTIFACT_WARMUP) as sp:
            for digest in self.store.usage_order():
                if (time.perf_counter() - t0) * 1000.0 >= max_ms:
                    budget_hit = "maxMs"
                    break
                if nbytes_total >= max_bytes:
                    budget_hit = "maxBytes"
                    break
                with self._lock:
                    if digest in self._loaded:
                        continue
                res = self.store.load_by_digest(digest)
                if res is None:
                    skipped += 1
                    continue
                compiled, nbytes = res
                with self._lock:
                    self._loaded[digest] = compiled
                loaded += 1
                nbytes_total += nbytes
            if sp is not None:
                sp.attrs["loaded"] = loaded
                sp.attrs["nbytes"] = nbytes_total
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            self.preloaded += loaded
            self.preload_ms += elapsed_ms
            self.preload_bytes += nbytes_total
        return {"enabled": True, "loaded": loaded, "skipped": skipped,
                "bytes": nbytes_total, "ms": round(elapsed_ms, 3),
                "budget_hit": budget_hit}

    def kernel_call(self, label: str, jitted, args, kwargs):
        """Dispatch one module-level jitted utility kernel through the
        artifact seam (see :class:`AotKernel` for the calling
        convention). The jitted original stays the correctness anchor:
        any signature whose AOT path misbehaves drops to it, permanently
        for that signature."""
        try:
            statics = tuple(sorted(kwargs.items()))
            skey = (label, statics, _signature(args))
        except Exception:
            return jitted(*args, **kwargs)
        with self._util_lock:
            entry = self._util.get(skey)
        if entry is None:
            entry = self._acquire_kernel(skey, jitted, args, kwargs)
        if entry is _FALLBACK:
            return jitted(*args, **kwargs)
        compiled, digest = entry
        try:
            out = compiled(*args)
        except Exception:
            # Dispatch rejection: the corrupt ladder's last rung.
            with self._util_lock:
                self._util[skey] = _FALLBACK
            self.discard(digest)
            return jitted(*args, **kwargs)
        self.note_use(digest)
        return out

    def _acquire_kernel(self, skey: tuple, jitted, args, kwargs):
        with self._util_lock:
            entry = self._util.get(skey)
            if entry is not None:
                return entry
            fields = key_fields("util", repr(skey[:2]), repr(skey[2]))
            compiled = self.fetch(fields)
            if compiled is None:
                try:
                    compiled = jitted.lower(*args, **kwargs).compile()
                except Exception:
                    self._util[skey] = _FALLBACK
                    return _FALLBACK
                self.put(fields, compiled)
            entry = (compiled, key_digest(fields))
            self._util[skey] = entry
            return entry

    def stats(self) -> dict:
        out = self.store.stats()
        with self._lock:
            out["warm_hits"] = self.warm_hits
            out["loaded_in_memory"] = len(self._loaded)
            out["preloaded"] = self.preloaded
            out["preload_ms"] = round(self.preload_ms, 3)
            out["preload_bytes"] = self.preload_bytes
        return out


class AotStage:
    """Bank-stage dispatch wrapper: per argument signature, try the
    artifact manager, else AOT-compile the inner jit wrapper
    (``lower().compile()`` — the same single compile jit would pay) and
    publish. The inner wrapper remains the correctness anchor: any
    signature whose AOT path misbehaves — un-lowerable arguments, a
    loaded executable rejecting the call — drops to it permanently,
    so the wrapped stage can never answer differently than the
    unwrapped one."""

    def __init__(self, inner, stage_key: tuple,
                 manager: ArtifactManager):
        self._inner = inner
        self._stage_repr = repr(stage_key)
        self._manager = manager
        self._lock = threading.Lock()
        # signature -> (compiled, artifact digest) | _FALLBACK.
        self._compiled: Dict[tuple, Tuple] = {}

    def __call__(self, *args, **kwargs):
        if kwargs:
            return self._inner(*args, **kwargs)
        try:
            sig = _signature(args)
        except Exception:
            return self._inner(*args)
        entry = self._compiled.get(sig)
        if entry is None:
            entry = self._acquire(sig, args)
        if entry is _FALLBACK:
            return self._inner(*args)
        compiled, digest = entry
        try:
            out = compiled(*args)
        except Exception:
            # Dispatch rejection (the ladder's last rung): evict the
            # artifact everywhere and answer from the jit wrapper.
            with self._lock:
                self._compiled[sig] = _FALLBACK
            self._manager.discard(digest)
            return self._inner(*args)
        self._manager.note_use(digest)
        return out

    def _acquire(self, sig: tuple, args):
        with self._lock:
            entry = self._compiled.get(sig)
            if entry is not None:
                return entry
            fields = key_fields("bank", self._stage_repr, repr(sig))
            compiled = self._manager.fetch(fields)
            if compiled is None:
                try:
                    compiled = self._inner.lower(*args).compile()
                except Exception:
                    self._compiled[sig] = _FALLBACK
                    return _FALLBACK
                self._manager.put(fields, compiled)
            entry = (compiled, key_digest(fields))
            self._compiled[sig] = entry
            return entry


# ---------------------------------------------------------------------------
# The per-root manager registry + the conf-driven entry points.
# ---------------------------------------------------------------------------


class _ManagerRegistry:
    """root dir -> manager; process-wide like the ProgramBank (two
    sessions over one lake share every loaded executable)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_root: Dict[str, ArtifactManager] = {}

    def get(self, root: str, max_bytes: int,
            usage_flush_ms: float) -> ArtifactManager:
        with self._lock:
            mgr = self._by_root.get(root)
            if mgr is None:
                mgr = ArtifactManager(ArtifactStore(
                    root, max_bytes, usage_flush_ms))
                self._by_root[root] = mgr
            else:
                # Budgets follow the most recent conf read (plain
                # attribute writes; racing sessions just disagree
                # briefly about a threshold).
                mgr.store.max_bytes = max_bytes
                mgr.store.usage_flush_ms = usage_flush_ms
        return mgr

    def all(self) -> list:
        with self._lock:
            return list(self._by_root.values())


_REGISTRY: Optional[_ManagerRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> _ManagerRegistry:
    """The process singleton; first use registers the "artifacts"
    metrics collector (the streaming get_queue idiom)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = _ManagerRegistry()
            from ..telemetry import metric_names as MN
            from ..telemetry.metrics import get_registry as _metrics
            _metrics().register_collector(
                MN.COLLECTOR_ARTIFACTS, _collector_stats)
        return _REGISTRY


def _collector_stats() -> dict:
    """Aggregate store counters across every root this process has
    opened (usually one lake)."""
    managers = get_registry().all()
    out = {"stores": len(managers)}
    for mgr in managers:
        for k, v in mgr.stats().items():
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + v
    return out


def manager_for(session) -> Optional[ArtifactManager]:
    """The session's artifact manager, or None when the store is
    disabled (the ONE cheap check every off-path pays) or no root can
    be resolved."""
    hs_conf = session.hs_conf
    if not hs_conf.artifacts_enabled():
        return None
    root = hs_conf.artifacts_dir()
    if not root:
        try:
            root = os.path.join(hs_conf.system_path(), ARTIFACT_DIR_NAME)
        except Exception:
            return None  # no system path configured: nowhere to persist
    return get_registry().get(root, hs_conf.artifacts_max_bytes(),
                              hs_conf.artifacts_usage_flush_ms())


def active_manager() -> Optional[ArtifactManager]:
    """The manager of the ACTIVE query's session (the dispatch seams'
    entry point — bank registration and MeshProgram compiles happen
    under an activated QueryContext)."""
    from ..serving.context import active_context
    ctx = active_context()
    if ctx is None or ctx.session is None:
        return None
    try:
        return manager_for(ctx.session)
    except Exception:
        return None


def maybe_wrap_stage(stage_key: tuple, fn):
    """ProgramBank registration hook: wrap a newly built jit-wrapper
    stage for AOT export/import iff the active session enables
    artifacts. SPMD stages are excluded — MeshProgram owns its own
    compile seam."""
    if not isinstance(stage_key, tuple) or not stage_key \
            or stage_key[0] == "spmd":
        return fn
    mgr = active_manager()
    if mgr is None:
        return fn
    return AotStage(fn, stage_key, mgr)


class AotKernel:
    """Module-level jitted utility kernel behind the artifact seam
    (ops/kernels.py wraps its serving-path helpers with this at import
    time — the op-by-op compile tail a cold boot would otherwise pay).

    Calling convention, enforced by the wrap sites: POSITIONAL arguments
    are dynamic (traced) and KEYWORD arguments are static — the
    AOT-compiled executable is invoked with the positionals only, the
    statics being baked into it. Stateless by design: the per-signature
    executable cache lives on the session's manager, so two lakes never
    share a wrongly keyed executable and the artifacts-off path is one
    ``active_manager()`` probe away from the raw jitted call."""

    __slots__ = ("_label", "_jitted")

    def __init__(self, label: str, jitted):
        self._label = label
        self._jitted = jitted

    def __call__(self, *args, **kwargs):
        try:
            mgr = active_manager()
        except Exception:
            mgr = None
        if mgr is None:
            return self._jitted(*args, **kwargs)
        return mgr.kernel_call(self._label, self._jitted, args, kwargs)


def wrap_kernel(label: str, jitted) -> AotKernel:
    """ops/kernels.py entry point (import-time)."""
    return AotKernel(label, jitted)


def preload(session) -> dict:
    """Boot preload within the session's budgets; the body behind
    ``Hyperspace.warmup()`` and the opt-in Session-init hook."""
    mgr = manager_for(session)
    if mgr is None:
        return {"enabled": False, "loaded": 0}
    return mgr.preload(session.hs_conf.artifacts_preload_max_ms(),
                       session.hs_conf.artifacts_preload_max_bytes())


def vacuum(session) -> dict:
    """Store maintenance riding ``Hyperspace.compact()``/``recover()``:
    crashed publication temps, unloadable (stale-runtime / corrupt)
    blobs, orphaned usage tallies, byte budget."""
    mgr = manager_for(session)
    if mgr is None:
        return {"enabled": False}
    out = mgr.store.vacuum()
    out["enabled"] = True
    return out


def flush_all() -> None:
    """Force every open store's usage sidecar to disk (tests and
    orderly shutdown; the serving path flushes on its own cadence)."""
    for mgr in get_registry().all():
        mgr.store.flush_usage(force=True)
