"""Config keys of the persistent compiled-program artifact store.

Key literals live here (not inline) because the static-analysis env/
config gates treat config.py as the one sanctioned reader and require
every ``hyperspace.tpu.*`` literal to appear in docs/configuration.md
(scripts/analysis: HS202 / doc-drift) — see §Artifacts there for
semantics and defaults.

No jax imports: config.py pulls this in at import time.
"""

from __future__ import annotations


# Directory name under the index system path holding the store (kept
# out of compaction/recovery's op-log walks: it contains no
# _hyperspace_log subdirectory, so the log sweeps skip it naturally).
ARTIFACT_DIR_NAME = "_hst_artifacts"

# Blob format version: part of every artifact key, so a layout change
# invalidates (silently misses) every existing blob instead of
# misparsing it.
ARTIFACT_FORMAT_VERSION = 1


class ArtifactConstants:
    # Master switch. Default OFF and byte-identical off: nothing is
    # wrapped, written, or read when false (tests assert the no-op).
    ENABLED = "hyperspace.tpu.artifacts.enabled"
    ENABLED_DEFAULT = "false"

    # Store directory override; empty means
    # ``<index system path>/_hst_artifacts`` (the lake-resident
    # default — artifacts live beside the indexes they serve).
    DIR = "hyperspace.tpu.artifacts.dir"
    DIR_DEFAULT = ""

    # Byte budget for resident blobs; publication past the budget
    # evicts least-used blobs first (usage sidecar order).
    MAX_BYTES = "hyperspace.tpu.artifacts.maxBytes"
    MAX_BYTES_DEFAULT = str(1 << 30)

    # Opt-in automatic preload at Session creation (warmup() is always
    # available explicitly).
    PRELOAD_ENABLED = "hyperspace.tpu.artifacts.preload.enabled"
    PRELOAD_ENABLED_DEFAULT = "false"

    # Preload budgets: stop loading once either is exhausted. Ordering
    # is by persisted usage tallies, so the budget is spent on the
    # hottest programs first.
    PRELOAD_MAX_MS = "hyperspace.tpu.artifacts.preload.maxMs"
    PRELOAD_MAX_MS_DEFAULT = "5000"
    PRELOAD_MAX_BYTES = "hyperspace.tpu.artifacts.preload.maxBytes"
    PRELOAD_MAX_BYTES_DEFAULT = str(256 << 20)

    # Min milliseconds between usage-sidecar flushes (rate limit on the
    # serving path; shutdown-less processes still persist tallies at
    # most this stale).
    USAGE_FLUSH_MS = "hyperspace.tpu.artifacts.usage.flushMs"
    USAGE_FLUSH_MS_DEFAULT = "500"
