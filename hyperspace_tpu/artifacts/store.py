"""Lake-resident store of serialized compiled executables.

THE serialization boundary: every ``jax.experimental
.serialize_executable`` call (and every pickle of a compiled object) in
the tree lives in this file — scripts/analysis HS331 pins executable
serialization to this module, the way the jit gate pins ``jax.jit`` to
the kernel modules. Everything above (manager.py, the bank/MeshProgram
seams) moves opaque compiled handles only.

Layout (under ``<root>`` — by default ``<system path>/_hst_artifacts``):

    v1/<digest>.hsa     one blob per compiled program
    v1/usage.json       persisted per-artifact usage tallies
    v1/.tmp-*           in-flight publications (vacuumed)

A blob is one utf-8 JSON header line carrying the FULL key (format
version, kind, stage fingerprint, signature digest, mesh signature,
jax/jaxlib versions, backend) plus the payload's length and md5,
followed by the binary payload. The filename digest is computed from
the same key fields, so a key mismatch (new jax version, different
mesh, different backend) addresses a file that does not exist — a
silent MISS that falls back to a normal compile, never an error. The
header is pure defense in depth: any mismatch or checksum failure on
read is the r14 spill-corrupt ladder — miss + evict + typed event
(``ArtifactMissEvent(reason="corrupt")``), never a wrong answer.

Publication is the op-log idiom: fsync'd temp + link-into-place
put-if-absent (losing a cross-process race is success — the winner's
bytes are the same program). The ``artifacts.write`` fault point sits
BETWEEN the temp write and the rename, so an injected kill -9 dies
mid-publication with the store still containing only whole blobs; the
crashed temp is swept by :meth:`ArtifactStore.vacuum` (riding
``Hyperspace.compact()``/``recover()``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..util import hashing
from ..util.file_utils import atomic_write_bytes
from .constants import ARTIFACT_FORMAT_VERSION

BLOB_SUFFIX = ".hsa"
TMP_PREFIX = ".tmp-"
USAGE_FILE = "usage.json"

# Key fields serialized into every header, in this order. "stage" is the
# md5 of the bank stage key repr; "sig" the md5 of the argument shape
# signature repr; "mesh" the mesh-signature repr ("" for single-device
# bank stages).
_KEY_FIELDS = ("format", "kind", "stage", "sig", "mesh",
               "jax", "jaxlib", "backend")


def runtime_env() -> Dict[str, str]:
    """The environment half of every artifact key: compiled executables
    are only loadable under the exact jax/jaxlib pair and backend that
    produced them — anything else must be a silent MISS."""
    import jax
    try:
        import jaxlib
        jaxlib_version = str(jaxlib.__version__)
    except Exception:
        jaxlib_version = "unknown"
    return {"jax": str(jax.__version__), "jaxlib": jaxlib_version,
            "backend": str(jax.default_backend())}


def key_fields(kind: str, stage_repr: str, sig_repr: str,
               mesh_repr: str = "",
               env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    env = env or runtime_env()
    return {
        "format": str(ARTIFACT_FORMAT_VERSION),
        "kind": kind,
        "stage": hashing.md5_hex(stage_repr),
        "sig": hashing.md5_hex(sig_repr),
        "mesh": mesh_repr,
        "jax": env["jax"], "jaxlib": env["jaxlib"],
        "backend": env["backend"],
    }


def key_digest(fields: Dict[str, str]) -> str:
    return hashing.md5_hex(
        repr(tuple(fields.get(k, "") for k in _KEY_FIELDS)))[:24]


# ---------------------------------------------------------------------------
# The serialization codec (the HS331-pinned calls).
# ---------------------------------------------------------------------------


def _serialize_compiled(compiled) -> bytes:
    """Compiled executable -> payload bytes. serialize() returns the
    xla-serialized blob plus the in/out treedefs the loader needs;
    treedefs pickle (probed on this jaxlib), so one pickle carries all
    three."""
    import pickle

    from jax.experimental import serialize_executable as _se
    blob, in_tree, out_tree = _se.serialize(compiled)
    return pickle.dumps((blob, in_tree, out_tree),
                        protocol=pickle.HIGHEST_PROTOCOL)


def _deserialize_compiled(payload: bytes):
    """Payload bytes -> loaded compiled executable. ZERO backend
    compiles (the whole point: the r07 counter stays flat on a warm
    boot); any failure here is the caller's corrupt ladder."""
    import pickle

    from jax.experimental import serialize_executable as _se
    blob, in_tree, out_tree = pickle.loads(payload)
    return _se.deserialize_and_load(blob, in_tree, out_tree)


class ArtifactStore:
    """One process-wide store per root directory (manager.py owns the
    registry). All shared mutable state — counters and usage tallies —
    moves under ``_lock`` (HS301 registry); file operations are atomic
    renames and need no lock."""

    def __init__(self, root: str, max_bytes: int,
                 usage_flush_ms: float = 500.0):
        self.root = root
        self.version_dir = os.path.join(
            root, f"v{ARTIFACT_FORMAT_VERSION}")
        self.max_bytes = max_bytes
        self.usage_flush_ms = usage_flush_ms
        self._lock = threading.Lock()
        # digest -> [use count, last-use sequence stamp]; merged with
        # the on-disk sidecar at init and on every flush (another
        # process's tallies survive ours).
        self._usage: Dict[str, List[int]] = {}
        self._usage_seq = 0
        self._dirty = False
        self._last_flush = 0.0
        self.hits = 0
        self.misses = 0
        self.corrupt = 0
        self.persists = 0
        self.persist_bytes = 0
        self.evictions = 0
        self._load_usage_locked()

    # ------------------------------------------------------------------
    # Publication (put-if-absent) + load (miss/corrupt ladder).
    # ------------------------------------------------------------------

    def blob_path(self, digest: str) -> str:
        return os.path.join(self.version_dir, digest + BLOB_SUFFIX)

    def publish(self, fields: Dict[str, str], compiled) -> bool:
        """Serialize + publish one compiled executable; True when this
        call's bytes landed. NEVER raises on the serving path: a
        publication failure (injected, out of disk, unserializable
        executable) costs only persistence, not the query."""
        from ..robustness import fault_names as _fltn
        from ..robustness import faults as _faults
        from ..telemetry import span_names as SN
        from ..telemetry import trace as _trace
        digest = key_digest(fields)
        path = self.blob_path(digest)
        if os.path.exists(path):
            return False
        tmp = None
        try:
            with _trace.span(SN.ARTIFACT_EXPORT) as sp:
                payload = _serialize_compiled(compiled)
                header = dict(fields)
                header["nbytes"] = len(payload)
                header["md5"] = hashing.md5_hex(payload)
                data = (json.dumps(header, sort_keys=True) + "\n")\
                    .encode("utf-8") + payload
                os.makedirs(self.version_dir, exist_ok=True)
                tmp = os.path.join(
                    self.version_dir,
                    f"{TMP_PREFIX}{os.getpid()}-{digest}")
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                # The kill -9 window the crash harness aims at: the temp
                # is fully written, the blob not yet linked — dying here
                # must leave nothing loadable (vacuum sweeps the temp).
                _faults.fault_point(_fltn.ARTIFACTS_WRITE)
                try:
                    os.link(tmp, path)
                    won = True
                except FileExistsError:
                    won = False  # concurrent publisher won; same bytes
                if sp is not None:
                    sp.attrs["nbytes"] = len(payload)
                    sp.attrs["published"] = won
            if won:
                with self._lock:
                    self.persists += 1
                    self.persist_bytes += len(payload)
                self._emit_event(
                    "persist", digest, fields, nbytes=len(payload))
                self._evict_over_budget()
            return won
        except Exception:
            return False
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    def load(self, fields: Dict[str, str]):
        """The compiled executable for this key, or None (silent MISS).
        A corrupt/truncated/mismatched blob is the r14 spill-corrupt
        ladder: miss + evict + typed event — never an error, never a
        wrong answer."""
        from ..robustness import fault_names as _fltn
        from ..robustness import faults as _faults
        from ..telemetry import span_names as SN
        from ..telemetry import trace as _trace
        digest = key_digest(fields)
        path = self.blob_path(digest)
        with _trace.span(SN.ARTIFACT_LOAD) as sp:
            try:
                _faults.fault_point(_fltn.ARTIFACTS_READ)
                with open(path, "rb") as f:
                    data = f.read()
            except Exception:
                # Absent (the common cold miss) or an injected/transient
                # read failure: plain miss, nothing to evict.
                self._miss(sp, digest, fields, reason="absent")
                return None
            try:
                head, sep, payload = data.partition(b"\n")
                if not sep:
                    raise ValueError("truncated header")
                header = json.loads(head.decode("utf-8"))
                for k in _KEY_FIELDS:
                    if str(header.get(k)) != str(fields.get(k, "")):
                        raise ValueError(f"key field {k!r} mismatch")
                if header.get("nbytes") != len(payload) \
                        or header.get("md5") != hashing.md5_hex(payload):
                    raise ValueError("payload checksum mismatch")
                compiled = _deserialize_compiled(payload)
            except Exception:
                self._quarantine(path)
                _faults.note(artifact_corruptions=1)
                self._miss(sp, digest, fields, reason="corrupt")
                return None
            with self._lock:
                self.hits += 1
            if sp is not None:
                sp.attrs["hit"] = True
                sp.attrs["nbytes"] = len(payload)
            self._emit_event("hit", digest, fields, nbytes=len(payload))
            return compiled

    def load_by_digest(self, digest: str):
        """Preload-path load: the key comes from the blob's own header
        (verified against the filename digest), not from a live compile
        site. Returns (compiled, payload bytes) or None — a header
        whose runtime env differs from ours is skipped silently (vacuum
        removes it); anything inconsistent is the corrupt ladder."""
        path = self.blob_path(digest)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        try:
            head, sep, payload = data.partition(b"\n")
            if not sep:
                raise ValueError("truncated header")
            header = json.loads(head.decode("utf-8"))
            fields = {k: str(header.get(k, "")) for k in _KEY_FIELDS}
            if key_digest(fields) != digest:
                raise ValueError("header does not match filename digest")
            env = runtime_env()
            if (fields["jax"], fields["jaxlib"], fields["backend"]) != \
                    (env["jax"], env["jaxlib"], env["backend"]):
                return None  # loadable only by the runtime that made it
            if header.get("nbytes") != len(payload) \
                    or header.get("md5") != hashing.md5_hex(payload):
                raise ValueError("payload checksum mismatch")
            compiled = _deserialize_compiled(payload)
        except Exception:
            from ..robustness import faults as _faults
            self._quarantine(path)
            _faults.note(artifact_corruptions=1)
            self._miss(None, digest, {}, reason="corrupt")
            return None
        with self._lock:
            self.hits += 1
        return compiled, len(payload)

    def _miss(self, sp, digest: str, fields: Dict[str, str],
              reason: str) -> None:
        with self._lock:
            self.misses += 1
            if reason == "corrupt":
                self.corrupt += 1
        if sp is not None:
            sp.attrs["hit"] = False
            sp.attrs["reason"] = reason
        self._emit_event("miss", digest, fields, reason=reason)

    @staticmethod
    def _quarantine(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass  # already evicted by a concurrent loader

    # ------------------------------------------------------------------
    # Usage tallies (the preload ordering input, persisted).
    # ------------------------------------------------------------------

    def record_use(self, digest: str) -> None:
        """Bump one artifact's tally; flushed to the sidecar at most
        every ``usage.flushMs`` (the r20 bugfix: bank hit tallies used
        to die with the process, so a restart had no preload order)."""
        with self._lock:
            self._usage_seq += 1
            entry = self._usage.setdefault(digest, [0, 0])
            entry[0] += 1
            entry[1] = self._usage_seq
            self._dirty = True
            due = (time.monotonic() - self._last_flush) * 1000.0 \
                >= self.usage_flush_ms
        if due:
            self.flush_usage()

    def flush_usage(self, force: bool = False) -> None:
        """Merge in-memory tallies with the on-disk sidecar and replace
        it atomically. Counts merge by max (same-process restarts and
        sibling processes both re-count from their own loads; max keeps
        the hottest observed tally without double-adding)."""
        with self._lock:
            if not self._dirty and not force:
                return
            mine = {k: list(v) for k, v in self._usage.items()}
            self._dirty = False
            self._last_flush = time.monotonic()
        disk = self._read_usage_file()
        for k, v in disk.items():
            cur = mine.get(k)
            if cur is None:
                mine[k] = list(v)
            else:
                mine[k] = [max(cur[0], v[0]), max(cur[1], v[1])]
        try:
            atomic_write_bytes(
                os.path.join(self.version_dir, USAGE_FILE),
                json.dumps({"version": 1, "tallies": mine},
                           sort_keys=True).encode("utf-8"),
                tmp_prefix=TMP_PREFIX)
        except OSError:
            pass  # tallies are advisory; never fail the serving path

    def _read_usage_file(self) -> Dict[str, List[int]]:
        try:
            with open(os.path.join(self.version_dir, USAGE_FILE),
                      "rb") as f:
                raw = json.loads(f.read().decode("utf-8"))
            return {str(k): [int(v[0]), int(v[1])]
                    for k, v in dict(raw.get("tallies", {})).items()}
        except Exception:
            return {}  # absent or corrupt sidecar: start cold

    def _load_usage_locked(self) -> None:
        self._usage = self._read_usage_file()
        self._usage_seq = max(
            [v[1] for v in self._usage.values()], default=0)

    def usage_order(self) -> List[str]:
        """Resident blob digests, hottest first (count, then recency) —
        the preload order."""
        with self._lock:
            tallies = {k: tuple(v) for k, v in self._usage.items()}
        out = []
        for digest, _nbytes in self._list_blobs():
            out.append((tallies.get(digest, (0, 0)), digest))
        out.sort(key=lambda t: (t[0][0], t[0][1]), reverse=True)
        return [d for _t, d in out]

    # ------------------------------------------------------------------
    # Budget eviction + vacuum.
    # ------------------------------------------------------------------

    def _list_blobs(self) -> List[Tuple[str, int]]:
        out: List[Tuple[str, int]] = []
        try:
            names = os.listdir(self.version_dir)
        except OSError:
            return out
        for name in sorted(names):
            if not name.endswith(BLOB_SUFFIX):
                continue
            try:
                nbytes = os.path.getsize(
                    os.path.join(self.version_dir, name))
            except OSError:
                continue  # concurrently evicted
            out.append((name[:-len(BLOB_SUFFIX)], nbytes))
        return out

    def total_bytes(self) -> int:
        return sum(n for _d, n in self._list_blobs())

    def _evict_over_budget(self) -> List[str]:
        """Delete coldest-first until resident bytes fit the budget.
        Safe against concurrent loaders: a loader that opened the file
        before the unlink keeps its bytes (POSIX), one that comes after
        sees a plain miss."""
        blobs = self._list_blobs()
        total = sum(n for _d, n in blobs)
        if total <= self.max_bytes:
            return []
        with self._lock:
            tallies = {k: tuple(v) for k, v in self._usage.items()}
        order = sorted(blobs,
                       key=lambda t: tallies.get(t[0], (0, 0)))
        evicted = []
        for digest, nbytes in order:
            if total <= self.max_bytes:
                break
            try:
                os.unlink(self.blob_path(digest))
            except OSError:
                continue
            total -= nbytes
            evicted.append(digest)
            with self._lock:
                self.evictions += 1
                self._usage.pop(digest, None)
                self._dirty = True
            self._emit_event("evict", digest, None, nbytes=nbytes)
        if evicted:
            self.flush_usage(force=True)
        return evicted

    def vacuum(self) -> Dict:
        """The maintenance sweep riding ``Hyperspace.compact()`` /
        ``recover()``: crashed publication temps, blobs no current
        runtime can ever load (other format/jax/jaxlib/backend —
        unreferenced by construction), unparseable blobs, sidecar
        entries with no blob, then the byte budget."""
        summary: Dict = {"tmp_removed": 0, "stale_removed": 0,
                         "corrupt_removed": 0, "evicted": 0}
        env = runtime_env()
        try:
            names = os.listdir(self.version_dir)
        except OSError:
            return summary
        for name in sorted(names):
            path = os.path.join(self.version_dir, name)
            if name.startswith(TMP_PREFIX):
                self._quarantine(path)
                summary["tmp_removed"] += 1
                continue
            if not name.endswith(BLOB_SUFFIX):
                continue
            header = self._read_header(path)
            if header is None:
                self._quarantine(path)
                summary["corrupt_removed"] += 1
            elif (str(header.get("format"))
                    != str(ARTIFACT_FORMAT_VERSION)
                    or header.get("jax") != env["jax"]
                    or header.get("jaxlib") != env["jaxlib"]
                    or header.get("backend") != env["backend"]):
                self._quarantine(path)
                summary["stale_removed"] += 1
        live = {d for d, _n in self._list_blobs()}
        with self._lock:
            for digest in list(self._usage):
                if digest not in live:
                    self._usage.pop(digest, None)
                    self._dirty = True
        summary["evicted"] = len(self._evict_over_budget())
        self.flush_usage(force=True)
        return summary

    @staticmethod
    def _read_header(path: str) -> Optional[dict]:
        try:
            with open(path, "rb") as f:
                head = f.readline(1 << 16)
            return json.loads(head.decode("utf-8"))
        except Exception:
            return None

    # ------------------------------------------------------------------
    # Observability.
    # ------------------------------------------------------------------

    def _emit_event(self, what: str, digest: str,
                    fields: Optional[Dict[str, str]], nbytes: int = 0,
                    reason: str = "") -> None:
        """One typed event per store decision, through the active query
        context's logger (the ProgramBank._emit pattern); store work
        outside any query — warmup, vacuum — stays silent and is
        summarized by its caller instead."""
        from ..serving.context import active_context
        ctx = active_context()
        if ctx is None or ctx.session is None:
            return
        try:
            from ..telemetry.events import (ArtifactEvictEvent,
                                            ArtifactHitEvent,
                                            ArtifactMissEvent,
                                            ArtifactPersistEvent)
            from ..telemetry.logging import get_logger
            cls = {"hit": ArtifactHitEvent, "miss": ArtifactMissEvent,
                   "persist": ArtifactPersistEvent,
                   "evict": ArtifactEvictEvent}[what]
            kw = dict(message=f"artifact {what} {digest}",
                      key_digest=digest, nbytes=nbytes,
                      kind=(fields or {}).get("kind", ""))
            if what == "miss":
                kw["reason"] = reason
            get_logger(ctx.session.hs_conf.event_logger_class())\
                .log_event(cls(**kw))
        except Exception:
            pass  # observability must never fail an execution

    def stats(self) -> dict:
        with self._lock:
            out = {
                "hits": self.hits,
                "misses": self.misses,
                "corrupt": self.corrupt,
                "persists": self.persists,
                "persist_bytes": self.persist_bytes,
                "evictions": self.evictions,
                "tallies": len(self._usage),
            }
        blobs = self._list_blobs()
        out["blobs"] = len(blobs)
        out["resident_bytes"] = sum(n for _d, n in blobs)
        return out
