"""Persistent compiled-program artifact store (r20).

Serialized XLA executables live on the lake beside the indexes they
serve, keyed (stage fingerprint, shape-class vector, mesh signature,
jax/jaxlib version, backend); a warm boot preloads them usage-ordered
and reaches first-query with compile count ~ 0. See store.py for the
blob protocol and manager.py for the dispatch seams.

Import-light on purpose: config.py reads the constants; jax loads only
when a dispatch seam or preload actually runs.
"""

from .constants import ArtifactConstants  # noqa: F401
