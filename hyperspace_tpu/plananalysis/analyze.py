"""explain_analyze: one post-execution report for one query.

``Hyperspace.explain_analyze(df)`` EXECUTES the plan under a dedicated
QueryContext with its trace forced on (``trace_force`` pins the sample
coin — the caller asked for THIS query's trace), then fuses every
observability surface the execution touched into one text report:

- the span-tree timeline with per-span wall + self times
  (telemetry/trace.py render_timeline);
- estimated-vs-actual rows for every reordered join step, with the
  per-step q-error (optimizer/join_order.py records + the executor's
  observed actuals — the feedback signal ROADMAP item 2a will close the
  loop on);
- per-query tallies: the context's io attribution, the result-cache
  lookup outcome (from the trace), and the process-delta of program-bank
  and robustness counters across exactly this execution.

Deltas are process-wide counters diffed around the execution, so a
CONCURRENT query's traffic can leak into them — explain_analyze is a
diagnostic for a quiet session, not a per-query accounting system (the
io numbers, from the context, ARE exact).
"""

from __future__ import annotations

import time

from ..telemetry import span_names as SN
from ..telemetry.trace import render_timeline


def _q_error(est: float, actual: int) -> float:
    est = max(float(est), 1.0)
    actual = max(float(actual), 1.0)
    return max(est / actual, actual / est)


def _join_lines(session) -> list:
    records = getattr(session, "_last_join_order", None) or []
    actuals = getattr(session, "_join_actuals", {})
    lines = []
    for r in records:
        order = r["order"] if r.get("reordered") else r.get("labels", [])
        head = "reordered" if r.get("reordered") else "kept"
        lines.append(f"chain [{', '.join(r.get('labels', []))}] {head}"
                     + (f" -> [{', '.join(order)}]"
                        if r.get("reordered") else ""))
        for s in r.get("steps", []):
            actual = actuals.get(s["key"])
            if actual is None:
                lines.append(f"  join +{s['right']}: est "
                             f"{s['est_rows']:.0f} rows, actual n/a")
            else:
                lines.append(
                    f"  join +{s['right']}: est {s['est_rows']:.0f} "
                    f"rows, actual {actual} "
                    f"(q-error {_q_error(s['est_rows'], actual):.2f})")
    return lines


def _delta(before: dict, after: dict) -> dict:
    # ONE diff implementation in the package: the exposition layer's
    # (nested dicts flattened, vanished keys handled).
    from ..telemetry.exposition import delta
    return delta(before, after)


def explain_analyze_string(session, plan) -> str:
    from ..robustness import faults as _faults
    from ..serving.context import QueryContext
    from ..serving.program_bank import get_bank

    ctx = QueryContext.for_session(session)
    ctx.trace_force = True
    # Reset the reorder records so the "Joins" section is attributable
    # to THIS execution — a result-cache hit runs no reorder pass and
    # must report no joins, not the previous query's (the same hazard
    # Session.execute resets _last_reason_collector for).
    session._last_join_order = None
    bank0 = get_bank().stats()
    rob0 = _faults.stats()
    t0 = time.perf_counter()
    table = session.execute(plan, context=ctx)
    elapsed_ms = (time.perf_counter() - t0) * 1000.0
    bank1 = get_bank().stats()
    rob1 = _faults.stats()
    tr = ctx.trace

    lines = ["== Explain Analyze =="]
    lines.append(f"query {ctx.query_id}: {elapsed_ms:.2f} ms, "
                 f"{table.num_rows} row(s)")

    lines.append("")
    lines.append("Trace:")
    if tr is not None:
        lines.extend(render_timeline(tr))
    else:
        lines.append("(no trace recorded)")

    join_lines = _join_lines(session)
    if join_lines:
        lines.append("")
        lines.append("Joins (estimated vs actual):")
        lines.extend(join_lines)

    lines.append("")
    lines.append("Tallies:")
    io = ctx.io_stats()
    lines.append(
        f"io: tasks={io['read_tasks']} bytes={io['read_bytes']} "
        f"read={io['read_seconds']:.3f}s wait={io['wait_seconds']:.3f}s "
        f"prefetch_items={io['prefetch_items']}")
    cache_line = "cache: no lookup (result cache off)"
    if tr is not None:
        lookups = tr.find(SN.CACHE_LOOKUP)
        if lookups:
            a = lookups[-1].attrs
            cache_line = (f"cache: {'hit' if a.get('hit') else 'miss'}"
                          + (f" tier={a['tier']}" if a.get("tier") else ""))
    lines.append(cache_line)
    bank_d = _delta(bank0, bank1)
    lines.append("bank: " + (" ".join(
        f"{k}={v:+g}" for k, v in sorted(bank_d.items()))
        if bank_d else "no traffic"))
    rob_d = _delta(rob0, rob1)
    lines.append("robustness: " + (" ".join(
        f"{k}={v:+g}" for k, v in sorted(rob_d.items()))
        if rob_d else "clean"))
    if tr is not None and tr.keep_reasons:
        lines.append(f"tail-keep marks: {', '.join(tr.keep_reasons)}")
    return "\n".join(lines)
