from .explain import explain_string  # noqa: F401
