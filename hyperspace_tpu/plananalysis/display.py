"""Display modes for explain output.

Parity reference: plananalysis/DisplayMode.scala — Console / PlainText /
HTML renderings share one buffer protocol; each mode defines its newline
and the begin/end tags wrapped around highlighted (changed) plan lines.
"""

from __future__ import annotations

import html as _html
from typing import List


class DisplayMode:
    """Rendering policy: newline + highlight delimiters."""

    new_line = "\n"
    highlight_begin = ""
    highlight_end = ""

    def escape(self, text: str) -> str:
        return text

    def wrap(self, body: str) -> str:
        return body


class PlainTextMode(DisplayMode):
    """No decoration — stable output for golden files and logs."""


class ConsoleMode(DisplayMode):
    """ANSI highlight for terminals (changed subtrees in yellow)."""

    highlight_begin = "\033[93m"
    highlight_end = "\033[0m"


class HTMLMode(DisplayMode):
    """HTML rendering: escaped text, <br> newlines, <b> highlights,
    wrapped in <pre> (parity: DisplayMode.scala HTML mode)."""

    new_line = "<br>"
    highlight_begin = "<b>"
    highlight_end = "</b>"

    def escape(self, text: str) -> str:
        return _html.escape(text)

    def wrap(self, body: str) -> str:
        return f"<pre>{body}</pre>"


_MODES = {
    "plaintext": PlainTextMode,
    "console": ConsoleMode,
    "html": HTMLMode,
}


def get_mode(name) -> DisplayMode:
    if isinstance(name, DisplayMode):
        return name
    cls = _MODES.get(str(name).lower())
    if cls is None:
        raise ValueError(
            f"Unknown display mode {name!r}; one of {sorted(_MODES)}")
    return cls()


class BufferStream:
    """Line buffer writing through a DisplayMode (parity:
    plananalysis/BufferStream.scala)."""

    def __init__(self, mode: DisplayMode):
        self.mode = mode
        self._lines: List[str] = []

    def write_line(self, text: str = "", highlight: bool = False) -> None:
        body = self.mode.escape(text)
        if highlight and text.strip():
            body = self.mode.highlight_begin + body + self.mode.highlight_end
        self._lines.append(body)

    def build(self) -> str:
        return self.mode.wrap(self.mode.new_line.join(self._lines))
