"""Explain API: lockstep diff of the plan with and without hyperspace rules.

Parity reference: plananalysis/PlanAnalyzer.scala:36-120 — builds two
executions (rules enabled/disabled), walks both plans in lockstep
highlighting the subtrees the rewrite changed, lists the indexes the
rewritten plan uses, and renders through a pluggable display mode
(Console / PlainText / HTML — plananalysis/DisplayMode.scala).
"""

from __future__ import annotations

from typing import List, Optional, Set, Tuple

from ..plan.nodes import IndexScan, LogicalPlan
from .display import BufferStream, DisplayMode, get_mode


def _used_indexes(plan: LogicalPlan):
    out = []
    for leaf in plan.collect_leaves():
        if isinstance(leaf, IndexScan):
            e = leaf.index_entry
            out.append(f"{e.name} (Type: {e.derivedDataset.kind_abbr}, "
                       f"LogVersion: {e.log_version})")
    return out


# ---------------------------------------------------------------------------
# Lockstep diff: mark every node inside a subtree the rewrite changed.
# ---------------------------------------------------------------------------

def _render(plan: LogicalPlan, depth: int = 0
            ) -> List[Tuple[LogicalPlan, int, str]]:
    rows = [(plan, depth, "  " * depth + plan.simple_string())]
    for c in plan.children:
        rows.extend(_render(c, depth + 1))
    return rows


def _mark_all(node: LogicalPlan, marks: Set[int]) -> None:
    marks.add(id(node))
    for c in node.children:
        _mark_all(c, marks)


def _diff_marks(a: LogicalPlan, b: LogicalPlan,
                marks_a: Set[int], marks_b: Set[int]) -> None:
    """Walk both trees in lockstep; where they diverge, highlight the whole
    differing subtree on each side (PlanAnalyzer highlights changed
    subtrees, not single lines)."""
    if a.tree_string() == b.tree_string():
        return
    same_head = (type(a) is type(b)
                 and a.simple_string() == b.simple_string()
                 and len(a.children) == len(b.children))
    if not same_head:
        _mark_all(a, marks_a)
        _mark_all(b, marks_b)
        return
    for ca, cb in zip(a.children, b.children):
        _diff_marks(ca, cb, marks_a, marks_b)


def _write_plan(buf: BufferStream, plan: LogicalPlan,
                marks: Optional[Set[int]]) -> None:
    for node, _depth, line in _render(plan):
        buf.write_line(line,
                       highlight=marks is not None and id(node) in marks)


def _header(buf: BufferStream, title: str) -> None:
    buf.write_line("=" * 60)
    buf.write_line(title)
    buf.write_line("=" * 60)


def explain_string(session, plan: LogicalPlan, verbose: bool = False,
                   mode="plaintext", diagnostics: bool = True) -> str:
    """``diagnostics=False`` renders the PLAN-ONLY explain (rewrite diff,
    indexes used, operator stats): the runtime sections (cache /
    compilation / io / spmd / serving) read process-lifetime counters,
    which golden plan-stability diffs must not depend on."""
    display: DisplayMode = get_mode(mode)
    was_enabled = session.is_hyperspace_enabled()
    try:
        session.enable_hyperspace()
        # Diagnostic pass: explain must not bump usage counts or emit
        # usage telemetry for a query it does not execute.
        with_index = session.optimize(plan, diagnostic=True)
    finally:
        if not was_enabled:
            session.disable_hyperspace()

    marks_with: Set[int] = set()
    marks_without: Set[int] = set()
    _diff_marks(with_index, plan, marks_with, marks_without)

    buf = BufferStream(display)
    _header(buf, "Plan with indexes:")
    _write_plan(buf, with_index, marks_with)
    buf.write_line()
    _header(buf, "Plan without indexes:")
    _write_plan(buf, plan, marks_without)
    buf.write_line()
    _header(buf, "Indexes used:")
    used = _used_indexes(with_index)
    for line in (used if used else ["<none>"]):
        buf.write_line(line)
    if diagnostics:
        _write_cache_section(buf, session, plan)
        _write_compilation_section(buf, session)
        _write_io_section(buf, session)
        _write_spmd_section(buf, session)
        _write_serving_section(buf, session)
        _write_robustness_section(buf, session)
        _write_slo_section(buf, session)
        _write_trace_section(buf, session)
    _write_advisor_section(buf, session, with_index)
    _write_join_order_section(buf, session)
    if verbose:
        buf.write_line()
        _header(buf, "Physical operator stats:")
        before = _count_nodes(plan)
        after = _count_nodes(with_index)
        for name in sorted(set(before) | set(after)):
            b, a = before.get(name, 0), after.get(name, 0)
            if b != a:
                buf.write_line(f"{name}: {b} -> {a}")
    return buf.build()


def _write_cache_section(buf: BufferStream, session,
                         plan: LogicalPlan) -> None:
    """Serving-cache observability (rendered only while the result cache
    is enabled, so the explain goldens of cache-less sessions are
    untouched): whether THIS query would be served from cache, plus the
    result-cache and HBM index-table-cache counters (the latter were
    previously counted in execution/index_cache.py but never shown)."""
    cache = session.result_cache
    if cache is None:
        return
    from ..serving.fingerprint import compute_key
    buf.write_line()
    _header(buf, "Result cache:")
    key = compute_key(session, plan)
    if key is None:
        buf.write_line("plan shape not cacheable")
    else:
        tier = cache.peek(key)
        if tier is not None:
            buf.write_line(
                f"result served from cache ({tier} tier, "
                f"key {key.digest()})")
        else:
            buf.write_line(
                f"miss - result will be computed and considered for "
                f"admission (key {key.digest()})")
    s = cache.stats()
    buf.write_line(
        f"result cache: hits={s['hits']} misses={s['misses']} "
        f"admissions={s['admissions']} evictions={s['evictions']} "
        f"entries={s['device_entries']}+{s['host_entries']} "
        f"bytes={s['device_nbytes']}+{s['host_nbytes']}")
    from ..execution import index_cache
    if index_cache.enabled():
        ic = index_cache.get_cache()
        buf.write_line(
            f"index table cache: hits={ic.hits} misses={ic.misses} "
            f"resident_bytes={ic.nbytes}")


def _write_compilation_section(buf: BufferStream, session) -> None:
    """Shape-class execution observability (execution/shapes.py): the
    process-lifetime XLA compile tally and the active bucketing knobs.
    Rendered only when bucketing is explicitly configured OR compiles
    have happened, so pristine-session explain goldens are untouched."""
    from ..execution import shapes
    total = shapes.compile_count()
    if total == 0:
        return
    p = shapes.params_from_conf(session.hs_conf)
    buf.write_line()
    _header(buf, "Compilation:")
    buf.write_line(
        f"xla compiles: total={total} "
        f"seconds={shapes.compile_seconds():.2f}")
    if p.enabled:
        buf.write_line(
            f"shape bucketing: on (growth={p.growth_factor:g} "
            f"minPad={p.min_pad} maxWaste={p.max_waste_ratio:g} "
            f"exactFallbackRows={p.exact_fallback_rows})")
    else:
        buf.write_line("shape bucketing: off (every data-dependent "
                       "length compiles its own programs)")


def _write_io_section(buf: BufferStream, session) -> None:
    """Parallel-I/O observability (parallel/io.py): the process-wide
    reader-pool counters and the read/decode vs consumer-wait time split.
    Rendered only once the pool or a prefetch stream has done work, so
    the explain goldens of io-less sessions are untouched."""
    from ..parallel import io as pio
    s = pio.pool_stats()
    if s["pooled_reads"] == 0 and s["prefetch_streams"] == 0:
        return
    p = pio.params_from_conf(session.hs_conf)
    buf.write_line()
    _header(buf, "I/O:")
    if p.enabled and p.resolved_threads() > 1:
        buf.write_line(
            f"reader pool: on (threads={p.resolved_threads()} "
            f"prefetchDepth={p.prefetch_depth} "
            f"maxInflightBytes={p.max_inflight_bytes})")
    else:
        buf.write_line("reader pool: off (reads run sequentially on the "
                       "calling thread)")
    buf.write_line(
        f"pooled reads: {s['pooled_reads']} fan-out(s), "
        f"{s['read_tasks']} file task(s), {s['read_bytes']} bytes; "
        f"prefetch: {s['prefetch_streams']} stream(s), "
        f"{s['prefetch_items']} item(s)")
    overlap = max(s["read_seconds"] - s["wait_seconds"], 0.0)
    buf.write_line(
        f"time split: read+decode={s['read_seconds']:.2f}s "
        f"consumer wait={s['wait_seconds']:.2f}s "
        f"(~{overlap:.2f}s of read hidden behind compute)")
    from ..execution import buffer_pool
    bp = buffer_pool.pool_stats()
    if bp["hits"] + bp["misses"] > 0:
        buf.write_line(
            f"buffer pool: hits={bp['hits']} misses={bp['misses']} "
            f"transfers={bp['transfers']} "
            f"decode_bytes_saved={bp['decode_bytes_saved']} "
            f"resident={bp['device_nbytes']}+{bp['host_nbytes']}")


def _write_spmd_section(buf: BufferStream, session) -> None:
    """Distributed-tier observability (execution/spmd.py over
    parallel/sharding.py): the mesh the dispatch would span, dispatch
    tallies, and the last program's compiled HLO collective counts.
    Rendered only once an SPMD program has actually dispatched (or a
    distributed build ran), so explain goldens of sessions that never
    went distributed are untouched."""
    import jax

    from ..execution import spmd
    from ..parallel import distributed_build, sharding
    total = spmd.DISPATCH_COUNT + distributed_build.DISPATCH_COUNT
    if total == 0:
        return
    buf.write_line()
    _header(buf, "Distributed:")
    conf = session.hs_conf
    n_dev = spmd._device_count(session)
    state = "on" if conf.distributed_enabled() else "off"
    buf.write_line(
        f"distributed: {state} (mesh devices={n_dev} "
        f"platform={jax.devices()[0].platform} "
        f"singleDevice={conf.distributed_single_device()} "
        f"fileAlignedScan="
        f"{'on' if conf.distributed_mesh_file_aligned_scan() else 'off'})")
    buf.write_line(
        f"dispatches: queries={spmd.DISPATCH_COUNT} "
        f"sorts={spmd.SORT_DISPATCH_COUNT} "
        f"builds={distributed_build.DISPATCH_COUNT} "
        f"mesh programs compiled={sharding.COMPILE_COUNT}")
    lc = spmd.last_collectives()
    if lc:
        pairs = " ".join(f"{k}={v}" for k, v in sorted(lc.items()) if v)
        buf.write_line(f"last program collectives: {pairs or 'none'}")


def _write_serving_section(buf: BufferStream, session) -> None:
    """Serving-tier observability (serving/frontend.py + program_bank):
    frontend admission/batching counters and the process-wide compiled-
    program bank. Rendered only when the serving tier is enabled on this
    session or a frontend has actually processed queries, so explain
    goldens of serving-less sessions are untouched."""
    from ..serving import frontend as fe
    from ..serving.program_bank import get_bank
    front = fe._DEFAULT
    enabled = session.hs_conf.serving_enabled()
    fstats = front.stats() if front is not None else None
    if not enabled and (fstats is None or fstats["submitted"] == 0):
        return
    buf.write_line()
    _header(buf, "Serving:")
    conf = session.hs_conf
    buf.write_line(
        f"frontend: {'on' if enabled else 'off'} "
        f"(maxConcurrency={conf.serving_max_concurrency()} "
        f"queueDepth={conf.serving_queue_depth()} "
        f"admission.maxBytes={conf.serving_admission_max_bytes()} "
        f"batching={'on' if conf.serving_batching_enabled() else 'off'})")
    if fstats is not None:
        s = fstats
        buf.write_line(
            f"queries: submitted={s['submitted']} admitted={s['admitted']} "
            f"rejected={s['rejected']} completed={s['completed']} "
            f"failed={s['failed']}")
        buf.write_line(
            f"batching: batches={s['batches']} "
            f"batched_queries={s['batched_queries']} "
            f"sweep_invocations={s['sweep_invocations']} "
            f"shared_scans={s['shared_scans']}")
    b = get_bank().stats()
    buf.write_line(
        f"program bank: stages={b['stages']} programs={b['programs']} "
        f"hits={b['hits']} misses={b['misses']} "
        f"evictions={b['evictions']}")


def _write_robustness_section(buf: BufferStream, session) -> None:
    """Robustness-layer observability (robustness/): the active
    deadline/retry/degradation knobs, armed fault points, and the
    process-lifetime counters of every ladder. Rendered only when the
    session configures the layer or something robustness-worthy already
    happened (a retry, an injected fault, a degradation, a
    cancellation), so pristine-session explain goldens are untouched."""
    from ..robustness import faults as _faults
    conf = session.hs_conf
    s = _faults.stats()
    armed = conf.robustness_fault_specs()
    configured = bool(armed) or conf.robustness_deadline_ms() > 0 or \
        not conf.robustness_degrade_enabled()
    if not configured and not any(s.values()):
        return
    buf.write_line()
    _header(buf, "Robustness:")
    buf.write_line(
        f"deadlineMs={conf.robustness_deadline_ms():g} "
        f"retry.maxAttempts={conf.robustness_retry_max_attempts()} "
        f"retry.baseMs={conf.robustness_retry_base_ms():g} "
        f"degrade={'on' if conf.robustness_degrade_enabled() else 'off'}")
    buf.write_line(
        f"fault points armed: {len(armed)}"
        + (f" ({', '.join(sorted(armed))})" if armed else ""))
    buf.write_line(
        f"retries={s['retries']} retry_failures={s['retry_failures']} "
        f"injected={s['injected']} "
        f"cancellations={s['deadline_cancellations']}")
    buf.write_line(
        f"degradations: spmd={s['degraded_spmd']} "
        f"bank_compile={s['degraded_bank_compile']} "
        f"device_put={s['degraded_device_put']} "
        f"spill_corrupt={s['spill_corruptions']} "
        f"sweep_member={s['member_fallbacks']} "
        f"worker_release={s['worker_releases']}")


def _write_slo_section(buf: BufferStream, session) -> None:
    """``Hyperspace.health()`` in the explain report: the current SLO
    verdict per armed objective plus the adaptive admission controller's
    stance when it is enabled. Rendered only once the monitor's window
    holds completed-query traffic, so explain goldens of sessions that
    never executed anything are untouched."""
    from ..telemetry.slo import get_monitor
    verdict = get_monitor().evaluate(session, emit=False)
    if not verdict.get("count"):
        return
    buf.write_line()
    _header(buf, "SLO:")
    buf.write_line(
        f"{'healthy' if verdict['healthy'] else 'BREACHED'} over "
        f"{verdict['window_s']:g}s window ({verdict['count']} queries, "
        f"{verdict['errors']} errors, {verdict['degraded']} degraded)")
    for name, obj in verdict["objectives"].items():
        if not obj["armed"]:
            continue
        observed = obj["observed"]
        buf.write_line(
            f"{name}: observed "
            f"{'n/a' if observed is None else f'{observed:.4g}'} "
            f"objective {obj['threshold']:g}"
            + (" BREACHED" if obj["breached"] else ""))
    if session.hs_conf.adaptive_admission_enabled():
        from ..adaptive.admission import get_controller
        s = get_controller().stats()
        buf.write_line(
            f"admission ({session.hs_conf.adaptive_admission_mode()}): "
            f"{'overloaded' if s['overloaded'] else 'admitting'} "
            f"breaches={s['breaches']} recoveries={s['recoveries']} "
            f"sheds={s['sheds']} degrades={s['degrades']}")


def _write_trace_section(buf: BufferStream, session) -> None:
    """Unified-tracing observability (telemetry/trace.py): the span
    timeline of the session's most recent traced query, with per-span
    wall and self times. Rendered only once a traced query has actually
    run (``_last_trace`` set), so explain goldens of trace-less sessions
    are untouched."""
    trace = getattr(session, "_last_trace", None)
    if trace is None:
        return
    from ..telemetry.trace import render_timeline
    buf.write_line()
    _header(buf, "Trace:")
    buf.write_line(
        f"trace {trace.trace_id}: {len(trace.spans)} span(s), "
        f"{trace.duration_s() * 1000:.2f} ms total "
        f"(hs.last_trace().to_chrome_json() exports it)")
    for line in render_timeline(trace):
        buf.write_line(line)


def _write_advisor_section(buf: BufferStream, session,
                           with_index: LogicalPlan) -> None:
    """Advisor observability (advisor/): workload-capture status and the
    session-local applied counts of the indexes this plan uses. Rendered
    only when capture is on or a workload was already recorded, so the
    explain goldens of advisor-less sessions are untouched."""
    from ..advisor.workload import log_for
    log = log_for(session)
    capture_on = session.hs_conf.advisor_capture_enabled()
    if len(log) == 0 and not capture_on:
        return
    buf.write_line()
    _header(buf, "Advisor:")
    buf.write_line(
        f"workload capture: {'on' if capture_on else 'off'} "
        f"({len(log)} record(s); "
        f"hs.recommend() ranks index candidates from them)")
    counts = session._index_usage_counts
    for leaf in with_index.collect_leaves():
        if isinstance(leaf, IndexScan):
            name = leaf.index_entry.name
            buf.write_line(f"index '{name}' applied "
                           f"{counts.get(name, 0)} time(s) this session")


def _write_join_order_section(buf: BufferStream, session) -> None:
    """Cost-based join-reorder observability (optimizer/join_order.py):
    the chain records of the diagnostic pass that just ran — chosen
    order plus per-step estimated rows, paired with actual executed
    output rows where the executor has recorded them. Rendered only
    while ``optimizer.joinReorder.enabled`` is true, so the explain
    goldens of reorder-less sessions are untouched.

    The estimate/actual pairing is BEST-EFFORT: ``_join_actuals`` keys
    are condition reprs shared session-wide, so if another query (or the
    same query under a different reorder setting) executed the same
    condition text over a *different* intermediate, the displayed actual
    is that execution's row count, not this step's. Re-keying by plan
    identity would break the pairing whenever the index rules rewrite
    the join below us (the common case this section exists to explain),
    which is the worse trade — explain() is diagnostic output, and the
    bench q-error path reads its actuals immediately after its own
    execution, where the pairing is exact."""
    if not session.hs_conf.join_reorder_enabled():
        return
    records = session._last_join_order
    if not records:
        return
    actuals = getattr(session, "_join_actuals", {})
    buf.write_line()
    _header(buf, "Join order:")
    for r in records:
        if r["reordered"]:
            buf.write_line(
                f"chain [{', '.join(r['labels'])}] reordered -> "
                f"[{', '.join(r['order'])}]")
        else:
            note = r.get("note", "kept")
            buf.write_line(
                f"chain [{', '.join(r['labels'])}] kept ({note})")
        for b in r["base"]:
            buf.write_line(
                f"  {b['label']}: est {b['est_rows']:.0f} rows")
        for s in r["steps"]:
            actual = actuals.get(s["key"])
            actual_str = f"{actual}" if actual is not None else "n/a"
            buf.write_line(
                f"  join +{s['right']}: est {s['est_rows']:.0f} rows, "
                f"actual {actual_str}")


def _count_nodes(plan: LogicalPlan):
    counts = {}

    def rec(node):
        counts[node.node_name] = counts.get(node.node_name, 0) + 1
        for c in node.children:
            rec(c)

    rec(plan)
    return counts
