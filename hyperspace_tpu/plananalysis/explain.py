"""Explain API: diff the plan with and without hyperspace rules.

Parity reference: plananalysis/PlanAnalyzer.scala:36-120 — builds two
executions (rules enabled/disabled), highlights the differing subtrees, and
lists the indexes the rewritten plan uses.
"""

from __future__ import annotations

from ..plan.nodes import IndexScan, LogicalPlan


def _used_indexes(plan: LogicalPlan):
    out = []
    for leaf in plan.collect_leaves():
        if isinstance(leaf, IndexScan):
            e = leaf.index_entry
            out.append(f"{e.name} (Type: {e.derivedDataset.kind_abbr}, "
                       f"LogVersion: {e.log_version})")
    return out


def explain_string(session, plan: LogicalPlan, verbose: bool = False) -> str:
    was_enabled = session.is_hyperspace_enabled()
    try:
        session.enable_hyperspace()
        with_index = session.optimize(plan)
    finally:
        if not was_enabled:
            session.disable_hyperspace()

    lines = []
    lines.append("=" * 60)
    lines.append("Plan with indexes:")
    lines.append("=" * 60)
    lines.append(with_index.tree_string())
    lines.append("")
    lines.append("=" * 60)
    lines.append("Plan without indexes:")
    lines.append("=" * 60)
    lines.append(plan.tree_string())
    lines.append("")
    lines.append("=" * 60)
    lines.append("Indexes used:")
    lines.append("=" * 60)
    used = _used_indexes(with_index)
    lines.extend(used if used else ["<none>"])
    if verbose:
        lines.append("")
        lines.append("=" * 60)
        lines.append("Physical operator stats:")
        lines.append("=" * 60)
        before = _count_nodes(plan)
        after = _count_nodes(with_index)
        for name in sorted(set(before) | set(after)):
            b, a = before.get(name, 0), after.get(name, 0)
            if b != a:
                lines.append(f"{name}: {b} -> {a}")
    return "\n".join(lines)


def _count_nodes(plan: LogicalPlan):
    counts = {}

    def rec(node):
        counts[node.node_name] = counts.get(node.node_name, 0) + 1
        for c in node.children:
            rec(c)

    rec(plan)
    return counts
