"""Core XLA compute kernels the engine is built from.

Everything here is jit-friendly (static shapes, no Python control flow on
traced values) except where a host sync is architecturally required (dynamic
result sizes: join output length, group count) — those sync points are single
scalars and are marked HOST SYNC.

These are the TPU-native equivalents of the distributed primitives catalogued
in SURVEY §2: hash-repartition (bucket_ids), sort-within-bucket
(lex_sort_indices), shuffle-free merge join (merge_join_indices over
co-partitioned buckets), and the lineage anti-filter (isin_sorted).

Shape-class execution (execution/shapes.py): the dynamic-size kernels accept
class-padded inputs with an explicit ``valid_count`` and can return padded
outputs (``padded_out=True``) so the executor keeps arrays on length classes
across operator boundaries instead of recompiling per exact length. The
padding contract each kernel honors internally:

- sorts prepend an is-pad key, so pad rows sort last and the valid prefix
  is byte-identical to the unpadded sort;
- searchsorted sentinels overwrite the pad tail with the dtype maximum and
  clamp the resulting bounds to the valid count;
- segment scatters route pad rows to an out-of-range segment id (XLA drops
  out-of-bounds scatter updates);
- expansion sizes (join match totals, group counts) are padded to their own
  length class before becoming static shape parameters.

Inputs that are tracers (the SPMD path calls these inside its own fused jit
programs, where shapes are already static) bypass padding entirely.
"""

from __future__ import annotations

import zlib
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException
from ..execution import shapes
from ..schema import BOOL, DATE, FLOAT32, FLOAT64, INT32, INT64, STRING

_M32 = np.uint32(0xFFFFFFFF)  # numpy scalar: no device alloc at import time


def _aot_kernel(label: str, jitted):
    """Route a module-level jitted utility kernel through the artifact
    store's AOT seam (artifacts/manager.py AotKernel): sessions that
    enable ``hyperspace.tpu.artifacts.enabled`` import/export these
    executables through the lake like banked stages, so a cold boot's
    op-by-op compile tail (gather, mask count, slice...) preloads too.
    Off sessions pay one manager probe and run the jitted original.
    CONVENTION at every wrapped call site: positional arguments are
    dynamic, keyword arguments are static."""
    try:
        from ..artifacts.manager import wrap_kernel
        return wrap_kernel(label, jitted)
    except Exception:
        return jitted


def _dtype_max(dtype):
    """Largest finite-orderable value of ``dtype`` (searchsorted sentinel:
    pads must not sort below any real key; ties are neutralized by
    clamping the searchsorted bounds to the valid count)."""
    if dtype == jnp.bool_:
        return True
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf
    return jnp.iinfo(dtype).max


# ---------------------------------------------------------------------------
# Fused jitted stage programs. Eager dispatch compiles each primitive
# separately — one tiny XLA program per (op, shape); a dynamic-size stage
# touching a fresh length class used to cost its whole op-chain in
# compiles. Each stage below is ONE compiled program per input signature
# instead. Python-int scalars (valid counts) become weak-typed scalar
# ARGUMENTS, so one program serves every count at a class.
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("ascending", "masked"))
def _sort_perm(operands: Tuple[jax.Array, ...], n,
               ascending: Tuple[bool, ...], masked: bool) -> jax.Array:
    phys = operands[0].shape[0]
    iota = jnp.arange(phys, dtype=jnp.int32)
    ops = [_sort_key_view(k, a) for k, a in zip(operands, ascending)]
    num_keys = len(ops)
    if masked:
        ops = [iota >= jnp.int32(n)] + ops  # pads sort last
        num_keys += 1
    out = jax.lax.sort(ops + [iota], num_keys=num_keys, is_stable=True)
    return out[-1]


_sort_perm = _aot_kernel("sort_perm", _sort_perm)


@jax.jit
def _merge_bounds(right_keys_sorted: jax.Array, left_keys: jax.Array,
                  n_l, n_r) -> Tuple[jax.Array, jax.Array, jax.Array]:
    phys_r = right_keys_sorted.shape[0]
    iota_r = jnp.arange(phys_r, dtype=jnp.int32)
    rk = jnp.where(iota_r < jnp.int32(n_r), right_keys_sorted,
                   jnp.asarray(_dtype_max(right_keys_sorted.dtype),
                               right_keys_sorted.dtype))
    lo = jnp.minimum(jnp.searchsorted(rk, left_keys, side="left"), n_r)
    hi = jnp.minimum(jnp.searchsorted(rk, left_keys, side="right"), n_r)
    counts = (hi - lo).astype(jnp.int32)
    phys_l = left_keys.shape[0]
    counts = jnp.where(jnp.arange(phys_l, dtype=jnp.int32) < jnp.int32(n_l),
                       counts, 0)
    return lo, counts, jnp.sum(counts)


@partial(jax.jit, static_argnames=("masked",))
def _group_ids_from_keys(keys: Tuple[jax.Array, ...], n, masked: bool
                         ) -> Tuple[jax.Array, jax.Array]:
    """Fused change-mask + running ids. Returns (gids, last valid id);
    with ``masked``, pad rows are parked at the out-of-range id ``phys``
    (>= any group count) so segment scatters drop them."""
    phys = keys[0].shape[0]
    change = jnp.zeros(phys, dtype=jnp.bool_)
    for k in keys:
        change = change | jnp.concatenate(
            [jnp.zeros(1, jnp.bool_), k[1:] != k[:-1]])
    if masked:
        iota = jnp.arange(phys, dtype=jnp.int32)
        valid = iota < jnp.int32(n)
        change = change & valid
        gids = jnp.cumsum(change.astype(jnp.int32))
        last = jnp.max(gids)  # pads keep the running id constant past n-1
        return jnp.where(valid, gids, jnp.int32(phys)), last
    gids = jnp.cumsum(change.astype(jnp.int32))
    return gids, gids[-1] if phys else jnp.int32(0)


@partial(jax.jit, static_argnames=("num_segments", "op"))
def _segment(data, gids, num_segments: int, op: str):
    fn = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
          "max": jax.ops.segment_max}[op]
    return fn(data, gids, num_segments=num_segments)


@partial(jax.jit, static_argnames=("num_segments", "op", "widen"))
def _segment_agg(data, validity, gids, num_segments: int, op: str,
                 widen: bool):
    """Fused per-group aggregate: accumulator widening / null-sentinel
    substitution / valid counting / mean division all inside ONE program
    (they used to be separate eager ops, one compile each per class).
    Returns (value, counts) — counts is the per-group valid count (None
    when the caller needs no validity and op is not a mean)."""
    counts = None
    if validity is not None or op == "mean":
        ones = jnp.ones(gids.shape[0], jnp.int64) if validity is None \
            else validity.astype(jnp.int64)
        counts = jax.ops.segment_sum(ones, gids, num_segments=num_segments)
    if op in ("sum", "mean"):
        acc = data.astype(jnp.float64) \
            if widen and jnp.issubdtype(data.dtype, jnp.floating) \
            else (data.astype(jnp.int64) if widen else data)
        if validity is not None:
            acc = jnp.where(validity, acc, jnp.zeros((), acc.dtype))
        sums = jax.ops.segment_sum(acc, gids, num_segments=num_segments)
        if op == "sum":
            return sums, counts
        return (sums.astype(jnp.float64) /
                jnp.maximum(counts, 1).astype(jnp.float64)), counts
    sentinel_max = op == "min"  # invalid rows push past every real value
    if validity is not None:
        if jnp.issubdtype(data.dtype, jnp.floating):
            sent = jnp.finfo(data.dtype).max if sentinel_max \
                else jnp.finfo(data.dtype).min
        else:
            sent = jnp.iinfo(data.dtype).max if sentinel_max \
                else jnp.iinfo(data.dtype).min
        data = jnp.where(validity, data, jnp.asarray(sent, data.dtype))
    fn = jax.ops.segment_min if op == "min" else jax.ops.segment_max
    return fn(data, gids, num_segments=num_segments), counts


@partial(jax.jit, static_argnames=("phys",))
def _global_gids(n, phys: int):
    """Segment ids for a global aggregate over a class-padded table: 0
    for valid rows, the (dropped) out-of-range id ``phys`` for pads."""
    iota = jnp.arange(phys, dtype=jnp.int32)
    return jnp.where(iota < jnp.int32(n), jnp.int32(0), jnp.int32(phys))


@partial(jax.jit, static_argnames=("num_segments",))
def _segment_heads(gids, arrays: Tuple[jax.Array, ...], num_segments: int):
    """Fused segment_first_index + gather: each segment's first row's
    values, for every array, in one program. Pad segments gather row 0
    via clip (never read as data)."""
    firsts = jax.ops.segment_min(
        jnp.arange(gids.shape[0], dtype=jnp.int32), gids,
        num_segments=num_segments)
    return tuple(jnp.take(a, firsts, axis=0, mode="clip") for a in arrays)


@partial(jax.jit, static_argnames=("num_segments", "op"))
def _gather_segment(partial_vals, order, gids, num_segments: int, op: str):
    """Fused gather + segment reduce (the two-phase combine step)."""
    fn = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
          "max": jax.ops.segment_max}[op]
    return fn(jnp.take(partial_vals, order, axis=0, mode="clip"), gids,
              num_segments=num_segments)


@partial(jax.jit, static_argnames=("num_segments",))
def _segment_ones(gids, num_segments: int):
    return jax.ops.segment_sum(jnp.ones(gids.shape[0], jnp.int64), gids,
                               num_segments=num_segments)


@partial(jax.jit, static_argnames=("num_segments",))
def _segment_iota_min(gids, num_segments: int):
    return jax.ops.segment_min(
        jnp.arange(gids.shape[0], dtype=jnp.int32), gids,
        num_segments=num_segments)


# ---------------------------------------------------------------------------
# Hashing (bucket assignment). murmur3-finalizer avalanche over uint32 lanes.
# ---------------------------------------------------------------------------

def _fmix32(x: jax.Array) -> jax.Array:
    x = x & _M32
    x = x ^ (x >> 16)
    x = (x * np.uint32(0x85EBCA6B)) & _M32
    x = x ^ (x >> 13)
    x = (x * np.uint32(0xC2B2AE35)) & _M32
    x = x ^ (x >> 16)
    return x


def fold_u32(data: jax.Array, dtype: str,
             dictionary: Optional[np.ndarray] = None) -> jax.Array:
    """Value-stable fold of a column into pre-avalanche uint32 words.

    This is the 64→32-bit (and string→crc) part of hash32_values, split out
    so the Pallas fused hash+bucket kernel (pallas_kernels.fused_hash_bucket)
    can consume the same fold and produce bit-identical hashes: the kernel
    applies the murmur finalizer to exactly these words.
    """
    if dtype == STRING:
        if dictionary is None:
            raise HyperspaceException("hash32 of string column requires dictionary")
        host_hashes = np.array(
            [zlib.crc32(s.encode("utf-8")) for s in dictionary], dtype=np.uint32) \
            if len(dictionary) else np.zeros(1, np.uint32)
        table = jnp.asarray(host_hashes)
        codes = jnp.clip(data, 0, max(len(dictionary) - 1, 0))
        return jnp.take(table, codes)
    if dtype in (INT32, DATE, BOOL):
        return data.astype(jnp.uint32)
    if dtype == INT64:
        u = data.astype(jnp.uint64)
        lo = (u & np.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (u >> np.uint64(32)).astype(jnp.uint32)
        return lo ^ (hi * np.uint32(0x9E3779B9))
    if dtype == FLOAT32:
        return jax.lax.bitcast_convert_type(data, jnp.uint32)
    if dtype == FLOAT64:
        bits = jax.lax.bitcast_convert_type(data, jnp.uint64)
        lo = (bits & np.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (bits >> np.uint64(32)).astype(jnp.uint32)
        return lo ^ (hi * np.uint32(0x9E3779B9))
    raise HyperspaceException(f"Cannot hash dtype {dtype}")


def hash32_values(data: jax.Array, dtype: str,
                  dictionary: Optional[np.ndarray] = None) -> jax.Array:
    """Stable 32-bit hash of a column's *values* (not its encoding).

    For strings the hash is computed from the dictionary entries' bytes on
    host (crc32) and gathered by code on device — so two tables with
    different dictionaries hash equal strings equally, which is what makes
    bucket co-partitioning work across index/source/appended data.
    """
    if shapes._is_tracer(data):
        return _fmix32(fold_u32(data, dtype, dictionary))
    if dtype == STRING:
        if dictionary is None:
            raise HyperspaceException("hash32 of string column requires dictionary")
        host_hashes = np.array(
            [zlib.crc32(s.encode("utf-8")) for s in dictionary], dtype=np.uint32) \
            if len(dictionary) else np.zeros(1, np.uint32)
        return _hash32_string(data, jnp.asarray(host_hashes))
    return _hash32_prim(data, dtype)


@jax.jit
def _hash32_string(codes: jax.Array, table: jax.Array) -> jax.Array:
    safe = jnp.clip(codes, 0, table.shape[0] - 1)
    return _fmix32(jnp.take(table, safe))


@partial(jax.jit, static_argnames=("dtype",))
def _hash32_prim(data: jax.Array, dtype: str) -> jax.Array:
    return _fmix32(fold_u32(data, dtype, None))


def _fmix32_host(x: int) -> int:
    """Host mirror of _fmix32 for single literals (bucket pruning)."""
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & 0xFFFFFFFF
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & 0xFFFFFFFF
    x ^= x >> 16
    return x


def hash_combine_host(h1: int, h2: int) -> int:
    """Host mirror of hash_combine."""
    return (h1 ^ ((h2 + 0x9E3779B9 + ((h1 << 6) & 0xFFFFFFFF) + (h1 >> 2))
                  & 0xFFFFFFFF)) & 0xFFFFFFFF


def hash32_value_host(value, dtype: str) -> int:
    """Host-side hash of one literal, identical to hash32_values on device.
    Used to compute the bucket a literal lands in (bucket pruning)."""
    import struct

    if dtype == STRING:
        return _fmix32_host(zlib.crc32(str(value).encode("utf-8")))
    if dtype in (INT32, DATE, BOOL):
        return _fmix32_host(int(value) & 0xFFFFFFFF)
    if dtype == INT64:
        u = int(value) & 0xFFFFFFFFFFFFFFFF
        lo, hi = u & 0xFFFFFFFF, u >> 32
        return _fmix32_host((lo ^ ((hi * 0x9E3779B9) & 0xFFFFFFFF)) & 0xFFFFFFFF)
    if dtype == FLOAT32:
        bits = struct.unpack("<I", struct.pack("<f", float(value)))[0]
        return _fmix32_host(bits)
    if dtype == FLOAT64:
        bits = struct.unpack("<Q", struct.pack("<d", float(value)))[0]
        lo, hi = bits & 0xFFFFFFFF, bits >> 32
        return _fmix32_host((lo ^ ((hi * 0x9E3779B9) & 0xFFFFFFFF)) & 0xFFFFFFFF)
    raise HyperspaceException(f"Cannot hash dtype {dtype}")


def hash_combine(h1: jax.Array, h2: jax.Array) -> jax.Array:
    """Boost-style combiner over uint32."""
    return (h1 ^ ((h2 + np.uint32(0x9E3779B9) + (h1 << 6) + (h1 >> 2)) & _M32)) & _M32


def bucket_ids(hashes: jax.Array, num_buckets: int) -> jax.Array:
    return (hashes % np.uint32(num_buckets)).astype(jnp.int32)


@jax.jit
def _masked_count(mask: jax.Array, n) -> Tuple[jax.Array, jax.Array]:
    """(mask with pad tail cleared, survivor count) in one program."""
    valid = jnp.arange(mask.shape[0], dtype=jnp.int32) < jnp.int32(n)
    mask = mask & valid
    return mask, jnp.sum(mask)


_masked_count = _aot_kernel("masked_count", _masked_count)


@partial(jax.jit, static_argnames=("size",))
def _nonzero_pad(mask: jax.Array, size: int) -> jax.Array:
    return jnp.flatnonzero(mask, size=size, fill_value=0)


_nonzero_pad = _aot_kernel("nonzero_pad", _nonzero_pad)


def mask_count_nonzero(mask, valid_rows: Optional[int], padded: bool):
    """Fused filter front-end: clear the pad tail, count survivors (one
    scalar HOST SYNC), and emit class-padded gather indices (filler 0).
    Two compiled programs per mask class instead of the ~6 eager ops of
    flatnonzero + masking."""
    from ..execution.shapes import padded_length
    if valid_rows is not None:
        mask, cnt = _masked_count(mask, valid_rows)
        m = int(cnt)  # HOST SYNC (single scalar)
    else:
        m = int(jnp.sum(mask))  # HOST SYNC (single scalar)
    size = padded_length(m) if padded else m
    return _nonzero_pad(mask, size=size), m


@partial(jax.jit, static_argnames=("dtype", "num_buckets", "check"))
def _composite_bucket_key(keys: jax.Array, n, dtype: str,
                          num_buckets: int, check: bool):
    """Fused hash -> bucket -> (bucket << 32 | biased key) composite for
    the shuffle-free merge-join probe (one program per class instead of
    the ~8-op eager chain). With ``check``, also returns max(|key[:n]|)
    so the caller's int32-fit test costs no extra program."""
    h = _fmix32(fold_u32(keys, dtype, None))
    b = (h % np.uint32(num_buckets)).astype(jnp.int32)
    comp = pack2_int32(b, keys.astype(jnp.int32))
    if not check:
        return comp, jnp.zeros((), keys.dtype)
    valid = jnp.arange(keys.shape[0], dtype=jnp.int32) < jnp.int32(n)
    extreme = jnp.max(jnp.where(valid, jnp.abs(keys),
                                jnp.zeros((), keys.dtype)))
    return comp, extreme


def bucket_composite_keys(keys: jax.Array, dtype: str, num_buckets: int,
                          valid_count: Optional[int] = None):
    """(composite probe keys, max |key| over the valid prefix — 0 when the
    dtype needs no int32-fit check)."""
    if shapes._is_tracer(keys):
        h = hash32_values(keys, dtype)
        comp = pack2_int32(bucket_ids(h, num_buckets),
                           keys.astype(jnp.int32))
        return comp, jnp.zeros((), keys.dtype)
    n = int(keys.shape[0]) if valid_count is None else int(valid_count)
    check = keys.dtype == jnp.int64 and keys.shape[0] > 0
    return _composite_bucket_key(keys, n, dtype, num_buckets, check)


# Fused predicate programs: one compiled program per predicate STRUCTURE
# (expression shape + column dtypes/validity + literal type tags — see
# evaluator.eval_predicate_mask_counted). Literal VALUES arrive as runtime
# scalar arguments, so a serving workload sweeping literals reuses one
# program. The builder also folds in the pad-tail mask and the survivor
# count, replacing the per-op compare/kleene/mask/count chain with a
# single program per (structure, class). The wrappers live in the
# process-wide PROGRAM BANK (serving/program_bank.py — the serving
# tier's explicit, size-bounded, instrumented registry; one session's
# warm-up pays every session's compiles); the jax.jit call stays HERE,
# in the lint-sanctioned instrumented module.


def _col_shape_vector(col_arrays) -> tuple:
    """Shape-class vector of a fused stage's inputs (the bank's hit/miss
    accounting unit; jax re-keys executables under the wrapper)."""
    return tuple(int(d.shape[0]) for d, _v in col_arrays)


def run_fused_predicate(key, builder, col_arrays, lit_args, n):
    """Run (compiling once per structure key x input signature) the fused
    predicate ``builder(col_arrays, lit_args, n) -> (mask, count)``.
    ``builder`` must be a pure function fully determined by ``key``.
    The bank is a bounded LRU over stages: overflowing evicts the single
    coldest structure (dropping its jit wrapper and compiled
    executables), never the whole map — a clear() would re-trace every
    hot predicate at once, the recompilation storm this layer exists to
    prevent."""
    from ..serving.program_bank import get_bank
    jitted = get_bank().lookup(("fused-predicate", key),
                               _col_shape_vector(col_arrays),
                               lambda: jax.jit(builder))
    return jitted(col_arrays, lit_args, n)


def run_fused_predicate_sweep(key, builder, col_arrays, lit_matrix, n,
                              batch: int):
    """Cross-query literal sweep: ONE invocation evaluating ``batch``
    literal vectors against the same columns — ``builder`` vmapped over
    the stacked literal axis. Returns (masks[batch, rows],
    counts[batch]). The stage key extends the single-query key with the
    batch class, so sweeps and singles never collide in the bank."""
    from ..serving.program_bank import get_bank
    jitted = get_bank().lookup(
        ("fused-predicate-sweep", key, batch),
        _col_shape_vector(col_arrays) + (batch,),
        lambda: jax.jit(jax.vmap(builder, in_axes=(None, 0, None))))
    return jitted(col_arrays, lit_matrix, n)


def run_fused_region(key, shape_vec, factory, args):
    """Run a whole-plan fused REGION program (execution/fusion.py): one
    jitted program per (region fingerprint, shape-class vector) in the
    process-wide ProgramBank. ``factory()`` must return a pure builder
    fully determined by ``key`` (the bank contract); the jax.jit call
    stays HERE, in the lint-sanctioned instrumented module, so the r07
    compile counter attributes every region compile."""
    from ..serving.program_bank import get_bank
    jitted = get_bank().lookup(("fused-region", key), tuple(shape_vec),
                               lambda: jax.jit(factory()))
    return jitted(args)


def nonzero_pad_indices(mask, size: int):
    """Class-padded indices of a mask's True entries (filler 0)."""
    return _nonzero_pad(mask, size=size)


@partial(jax.jit, static_argnames=("is_and",))
def _kleene_jit(ld, lv, rd, rv, is_and: bool):
    """Fused Kleene 3-valued AND/OR (TRUE OR NULL = TRUE, FALSE AND NULL
    = FALSE). ``lv``/``rv`` may be None (all-valid side). Returns
    (true, known)."""
    n = ld.shape[0]
    lvv = lv if lv is not None else jnp.ones(n, jnp.bool_)
    rvv = rv if rv is not None else jnp.ones(n, jnp.bool_)
    lt, lf = lvv & ld, lvv & ~ld
    rt, rf = rvv & rd, rvv & ~rd
    if is_and:
        true, false = lt & rt, lf | rf
    else:
        true, false = lt | rt, lf & rf
    return true, true | false


_kleene_jit = _aot_kernel("kleene", _kleene_jit)


def kleene_and_or(ld, lv, rd, rv, is_and: bool):
    if shapes._is_tracer(ld):  # SPMD evaluates expressions inside its jit
        n = ld.shape[0]
        lvv = lv if lv is not None else jnp.ones(n, jnp.bool_)
        rvv = rv if rv is not None else jnp.ones(n, jnp.bool_)
        lt, lf = lvv & ld, lvv & ~ld
        rt, rf = rvv & rd, rvv & ~rd
        true, false = (lt & rt, lf | rf) if is_and else (lt | rt, lf & rf)
        return true, true | false
    return _kleene_jit(ld, lv, rd, rv, is_and=is_and)


def gather_arrays(indices, arrays):
    """Fused multi-array row gather: one compiled program per signature
    instead of one take per column. Out-of-range indices (pad tails of
    class-padded index arrays) clip — clipped rows land in the pad region
    of the result and are never read as data."""
    arrays = tuple(arrays)
    if shapes._is_tracer(indices) or any(shapes._is_tracer(a)
                                         for a in arrays):
        return tuple(jnp.take(a, indices, axis=0, mode="clip")
                     for a in arrays)
    return _gather_jit(indices, arrays)


@jax.jit
def _gather_jit(indices, arrays: Tuple[jax.Array, ...]):
    return tuple(jnp.take(a, indices, axis=0, mode="clip") for a in arrays)


_gather_jit = _aot_kernel("gather", _gather_jit)


@partial(jax.jit, static_argnames=("start", "stop"))
def _slice_jit(arrays: Tuple[jax.Array, ...], start: int, stop: int):
    return tuple(a[start:stop] for a in arrays)


_slice_jit = _aot_kernel("slice", _slice_jit)


@partial(jax.jit, static_argnames=("target",))
def _pad_jit(arr, fill, target: int):
    return jax.lax.pad(arr, jnp.asarray(fill, arr.dtype),
                       [(0, target - arr.shape[0], 0)])


_pad_jit = _aot_kernel("pad", _pad_jit)


def pad_array(arr, fill, target: int):
    """shapes.pad_to device back-end: ONE program per (class, dtype,
    fill signature) — the eager spelling paid a convert + a pad program
    and neither survived a process restart."""
    return _pad_jit(arr, fill, target=target)


@jax.jit
def _adjacent_dup_jit(codes: jax.Array) -> jax.Array:
    return jnp.any(codes[1:] == codes[:-1])


_adjacent_dup_jit = _aot_kernel("adjacent_dup", _adjacent_dup_jit)


def has_adjacent_duplicates(codes) -> jax.Array:
    """True iff a SORTED key vector has equal neighbors (the fused-join
    m:n probe-side check): two slices + eq + any fused in one program."""
    return _adjacent_dup_jit(codes)


@partial(jax.jit, static_argnames=("dtype",))
def _cast_jit(arr, dtype: str):
    return arr.astype(jnp.dtype(dtype))


_cast_jit = _aot_kernel("cast", _cast_jit)


def cast_array(arr, dtype):
    """Dtype cast as one banked program (callers should skip the call
    entirely when the dtype already matches)."""
    return _cast_jit(arr, dtype=jnp.dtype(dtype).name)


def slice_arrays(arrays, start: int, stop: int):
    """Fused multi-array row slice: one compiled program per (signature,
    start, stop) instead of one slice per column buffer (Table.slice /
    Table.compact). NOTE the bounds are static — a data-dependent stop
    still compiles per distinct value, which is why final results trim at
    the host boundary instead (executor.execute) and only interior
    compaction boundaries (outer joins, windows, SPMD leaves) pay this."""
    arrays = tuple(arrays)
    if any(shapes._is_tracer(a) for a in arrays):
        return tuple(a[start:stop] for a in arrays)
    return _slice_jit(arrays, start=start, stop=stop)


# ---------------------------------------------------------------------------
# Sorting.
# ---------------------------------------------------------------------------

def _sort_key_view(data: jax.Array, ascending: bool) -> jax.Array:
    """Transform a key column so ascending lax.sort realizes the requested
    direction (numeric negate; safe for codes/int/float w/o NaN)."""
    if ascending:
        return data
    if data.dtype == jnp.bool_:
        return ~data
    return -data


def lex_sort_indices(keys: Sequence[jax.Array],
                     ascending: Optional[Sequence[bool]] = None,
                     valid_count: Optional[int] = None,
                     padded_out: bool = False,
                     pad: bool = True) -> jax.Array:
    """Indices that stably sort by keys[0], then keys[1], ... (lexicographic).

    lax.sort sorts by the leading operands; we append iota as the payload.

    Shape classes: inputs longer than ``valid_count`` (or padded here to
    their length class) get a leading is-pad sort key, so pad rows land
    after every real row and the valid prefix of the permutation is
    byte-identical to the unpadded sort. ``padded_out`` keeps the padded
    permutation (pad entries index pad rows) for padded gathers.
    """
    if ascending is None:
        ascending = [True] * len(keys)
    phys = int(keys[0].shape[0])
    n = phys if valid_count is None else int(valid_count)
    if shapes._is_tracer(keys[0]) or phys == 0:
        iota = jnp.arange(phys, dtype=jnp.int32)
        operands = [_sort_key_view(k, a)
                    for k, a in zip(keys, ascending)] + [iota]
        out = jax.lax.sort(operands, num_keys=len(keys), is_stable=True)
        return out[-1]
    if valid_count is None and pad:
        # ``pad=False`` opts out for whole-dataset work at a stable
        # per-dataset length (the index build): padding there buys no
        # compile reuse and costs real sort work on the tail.
        cls = shapes.padded_length(phys)
        if cls != phys:
            keys = [shapes.pad_to(k, cls) for k in keys]
            phys = cls
    padded = phys != n
    perm = _sort_perm(tuple(keys), n, ascending=tuple(ascending),
                      masked=padded)
    if padded and not padded_out:
        return shapes.unpad(perm, n)
    return perm


# ---------------------------------------------------------------------------
# Merge join over sorted keys.
# ---------------------------------------------------------------------------

def merge_join_indices(left_keys: jax.Array, right_keys_sorted: jax.Array,
                       return_counts: bool = False,
                       left_valid: Optional[int] = None,
                       right_valid: Optional[int] = None,
                       padded_out: bool = False):
    """Inner equi-join: for each left row, all matching right rows.

    ``right_keys_sorted`` must be ascending over its valid prefix. Returns
    (left_idx, right_idx) gather indices — plus the per-left-row match
    counts when ``return_counts`` (outer joins pad count-0 rows). Output
    length is data-dependent → one scalar HOST SYNC.

    Shape classes: padded inputs declare their valid prefix via
    ``left_valid``/``right_valid`` (exact inputs are padded here). The pad
    tail of the right side is overwritten with the dtype maximum to keep
    the searchsorted precondition, the bounds are clamped to the valid
    count (which also neutralizes real keys tying with the sentinel), and
    pad left rows contribute zero matches. The expansion size is padded to
    its own length class so one compiled expansion program serves every
    total in the class. With ``padded_out`` the padded (left_idx,
    right_idx, total) triple is returned for padded gathers (the tail of
    a padded expansion repeats in-bounds indices).
    """
    if shapes._is_tracer(left_keys):
        # The expansion length is data-dependent: under tracing the
        # host sync it requires is impossible (int() of a tracer is a
        # ConcretizationTypeError — the HS311 bug class). Trace-side
        # join programs precompute static capacities instead
        # (parallel/sharding.py); fail typed rather than deep inside
        # jax internals.
        raise HyperspaceException(
            "merge_join_indices cannot run under tracing: the join "
            "expansion length is data-dependent and would need a "
            "device->host sync. Traced callers must precompute a "
            "static match capacity (see parallel/sharding.py).")
    n_l = int(left_keys.shape[0]) if left_valid is None else int(left_valid)
    n_r = int(right_keys_sorted.shape[0]) if right_valid is None \
        else int(right_valid)
    if left_valid is None:
        left_keys = shapes.pad_to(
            left_keys, shapes.padded_length(n_l))
    if right_valid is None:
        right_keys_sorted = shapes.pad_to(
            right_keys_sorted, shapes.padded_length(n_r))
    if left_keys.dtype != right_keys_sorted.dtype:
        # One comparable dtype before the fused program (mixed-width int
        # keys reach here via executor joins).
        wide = jnp.promote_types(left_keys.dtype, right_keys_sorted.dtype)
        left_keys = left_keys.astype(wide)
        right_keys_sorted = right_keys_sorted.astype(wide)
    lo, counts, total_dev = _merge_bounds(right_keys_sorted, left_keys,
                                          n_l, n_r)
    total = int(total_dev)  # HOST SYNC (single scalar).
    cls_t = shapes.padded_length(total)
    li, ri = _expand_matches(counts, lo, cls_t)
    if padded_out:
        if return_counts:
            return li, ri, total, counts
        return li, ri, total
    li, ri = shapes.unpad(li, total), shapes.unpad(ri, total)
    if return_counts:
        return li, ri, shapes.unpad(counts, n_l)
    return li, ri


@partial(jax.jit, static_argnames=("total",))
def _expand_matches(counts: jax.Array, lo: jax.Array, total: int
                    ) -> Tuple[jax.Array, jax.Array]:
    n_left = counts.shape[0]
    left_idx = jnp.repeat(jnp.arange(n_left, dtype=jnp.int32), counts,
                          total_repeat_length=total)
    starts = jnp.cumsum(counts) - counts
    base = jnp.repeat(starts.astype(jnp.int32), counts, total_repeat_length=total)
    within = jnp.arange(total, dtype=jnp.int32) - base
    right_idx = jnp.repeat(lo.astype(jnp.int32), counts,
                           total_repeat_length=total) + within
    # NOTE on padded totals: jnp.repeat pads its output by repeating
    # trailing values, so the tail of a padded expansion can hold
    # out-of-range right indices — consumers gather with clip mode and
    # slice to the true total before anything order-sensitive.
    return left_idx, right_idx


def change_mask(sorted_keys: Sequence[jax.Array]) -> jax.Array:
    """True where a row's key tuple differs from the previous row's (rows
    already sorted by the keys); row 0 is False."""
    n = int(sorted_keys[0].shape[0])
    change = jnp.zeros(n, dtype=jnp.bool_)
    for k in sorted_keys:
        change = change | jnp.concatenate(
            [jnp.zeros(1, jnp.bool_), k[1:] != k[:-1]])
    return change


def dense_rank(keys: Sequence[jax.Array]) -> jax.Array:
    """Dense rank of each row's key *tuple* in lexicographic order.

    Equal tuples get equal ranks and ranks are order-preserving, so a
    multi-column equi-join reduces to a single int32-key join on the ranks
    of the two sides' concatenated key columns. Fully on device — no host
    sync (the consumer never needs the rank count).
    """
    n = int(keys[0].shape[0])
    if n == 0:
        return jnp.zeros(0, jnp.int32)
    order = lex_sort_indices(keys)
    change = change_mask([jnp.take(k, order) for k in keys])
    gids = jnp.cumsum(change.astype(jnp.int32))
    return jnp.zeros(n, jnp.int32).at[order].set(gids)


def pack2_int32(a: jax.Array, b: jax.Array) -> jax.Array:
    """Pack two int32 key columns into one int64 composite key.

    ``b`` is sign-biased (XOR 0x80000000) so the packed composite orders the
    same as (a asc, b signed-asc) — without the bias, negative ``b`` values
    sort above positive ones in the low 32 bits and break the merge join's
    sortedness precondition.
    """
    b_biased = (b.astype(jnp.int64) ^ np.int64(0x80000000)) & np.int64(0xFFFFFFFF)
    return (a.astype(jnp.int64) << np.int64(32)) | b_biased


# ---------------------------------------------------------------------------
# Grouping / segmented aggregation (over sorted group keys).
# ---------------------------------------------------------------------------

def group_ids_from_sorted(keys: Sequence[jax.Array],
                          valid_count: Optional[int] = None,
                          padded_out: bool = False) -> Tuple[jax.Array, int]:
    """Segment ids for rows already sorted by ``keys``.

    Returns (group_id per row, number of groups). One scalar HOST SYNC.

    Shape classes: with ``padded_out`` the ids stay at the padded input
    length, pad rows carrying an out-of-range id (the array's physical
    length — always >= the group count), so segment scatters drop them.
    """
    phys = int(keys[0].shape[0])
    n = phys if valid_count is None else int(valid_count)
    if n == 0:
        return jnp.zeros(phys if padded_out else 0, jnp.int32), 0
    padded = phys != n
    gids, last = _group_ids_from_keys(tuple(keys), n, masked=padded)
    num_groups = int(last) + 1  # HOST SYNC (single scalar).
    if padded and not padded_out:
        return shapes.unpad(gids, n), num_groups
    return gids, num_groups


def _segment_cap(num_groups: int, gids) -> int:
    """Static segment count for the scatter: the group count's length
    class (out-of-range pad ids land in dropped/sliced segments)."""
    if shapes._is_tracer(gids):
        return num_groups
    return max(shapes.padded_length(num_groups), num_groups)


def segment_sum(data: jax.Array, gids: jax.Array, num_groups: int,
                padded_out: bool = False) -> jax.Array:
    if shapes._is_tracer(data) or shapes._is_tracer(gids):
        return jax.ops.segment_sum(data, gids, num_segments=num_groups)
    out = _segment(data, gids, _segment_cap(num_groups, gids), "sum")
    return out if padded_out else shapes.unpad(out, num_groups)


def segment_count(gids: jax.Array, num_groups: int,
                  validity: Optional[jax.Array] = None,
                  padded_out: bool = False) -> jax.Array:
    if validity is None:
        if shapes._is_tracer(gids):
            return jax.ops.segment_sum(jnp.ones(gids.shape[0], jnp.int64),
                                       gids, num_segments=num_groups)
        out = _segment_ones(gids, _segment_cap(num_groups, gids))
        return out if padded_out else shapes.unpad(out, num_groups)
    return segment_sum(validity.astype(jnp.int64), gids, num_groups,
                       padded_out=padded_out)


def segment_min(data: jax.Array, gids: jax.Array, num_groups: int,
                padded_out: bool = False) -> jax.Array:
    if shapes._is_tracer(data) or shapes._is_tracer(gids):
        return jax.ops.segment_min(data, gids, num_segments=num_groups)
    out = _segment(data, gids, _segment_cap(num_groups, gids), "min")
    return out if padded_out else shapes.unpad(out, num_groups)


def segment_max(data: jax.Array, gids: jax.Array, num_groups: int,
                padded_out: bool = False) -> jax.Array:
    if shapes._is_tracer(data) or shapes._is_tracer(gids):
        return jax.ops.segment_max(data, gids, num_segments=num_groups)
    out = _segment(data, gids, _segment_cap(num_groups, gids), "max")
    return out if padded_out else shapes.unpad(out, num_groups)


def segment_agg(data: jax.Array, validity, gids: jax.Array,
                num_groups: int, op: str, widen: bool = True,
                padded_out: bool = False):
    """Fused null-aware per-group aggregate (see _segment_agg). Returns
    (value, per-group valid counts or None)."""
    cap = _segment_cap(num_groups, gids)
    value, counts = _segment_agg(data, validity, gids, cap, op, widen)
    if not padded_out:
        value = shapes.unpad(value, num_groups)
        if counts is not None:
            counts = shapes.unpad(counts, num_groups)
    return value, counts


def segment_heads(gids: jax.Array, arrays, num_groups: int,
                  padded_out: bool = False):
    """Each segment's first row's values for every array in ``arrays``
    (fused first-index + gather; rows sorted by group key)."""
    cap = _segment_cap(num_groups, gids)
    out = _segment_heads(gids, tuple(arrays), cap)
    if not padded_out:
        out = tuple(shapes.unpad(a, num_groups) for a in out)
    return out


def gather_segment(partial_vals: jax.Array, order: jax.Array,
                   gids: jax.Array, num_groups: int, op: str,
                   padded_out: bool = False) -> jax.Array:
    """Fused gather-through-permutation + segment reduce (two-phase
    aggregation's combine step)."""
    cap = _segment_cap(num_groups, gids)
    out = _gather_segment(partial_vals, order, gids, cap, op)
    return out if padded_out else shapes.unpad(out, num_groups)


def global_segment_ids(valid_count: int, phys: int) -> jax.Array:
    """Segment ids for a global aggregate over a class-padded table."""
    return _global_gids(valid_count, phys=phys)


def segment_first_index(gids: jax.Array, num_groups: int,
                        padded_out: bool = False) -> jax.Array:
    """Index of each group's first row (rows sorted by group key). In a
    padded output, segments past the group count hold the int32 maximum
    (segment_min identity) — gather through them with clip mode only."""
    if shapes._is_tracer(gids):
        return jax.ops.segment_min(
            jnp.arange(gids.shape[0], dtype=jnp.int32), gids,
            num_segments=num_groups)
    out = _segment_iota_min(gids, _segment_cap(num_groups, gids))
    return out if padded_out else shapes.unpad(out, num_groups)


# ---------------------------------------------------------------------------
# Membership (lineage anti-filter: Not(In(lineage, deletedIds))).
# ---------------------------------------------------------------------------

def isin_sorted(data: jax.Array, sorted_values: jax.Array) -> jax.Array:
    """Vectorized membership of ``data`` in ascending ``sorted_values``."""
    if sorted_values.shape[0] == 0:
        return jnp.zeros(data.shape[0], jnp.bool_)
    pos = jnp.searchsorted(sorted_values, data)
    pos = jnp.clip(pos, 0, sorted_values.shape[0] - 1)
    return jnp.take(sorted_values, pos) == data
