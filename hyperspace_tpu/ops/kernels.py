"""Core XLA compute kernels the engine is built from.

Everything here is jit-friendly (static shapes, no Python control flow on
traced values) except where a host sync is architecturally required (dynamic
result sizes: join output length, group count) — those sync points are single
scalars and are marked HOST SYNC.

These are the TPU-native equivalents of the distributed primitives catalogued
in SURVEY §2: hash-repartition (bucket_ids), sort-within-bucket
(lex_sort_indices), shuffle-free merge join (merge_join_indices over
co-partitioned buckets), and the lineage anti-filter (isin_sorted).
"""

from __future__ import annotations

import zlib
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException
from ..schema import BOOL, DATE, FLOAT32, FLOAT64, INT32, INT64, STRING

_M32 = np.uint32(0xFFFFFFFF)  # numpy scalar: no device alloc at import time


# ---------------------------------------------------------------------------
# Hashing (bucket assignment). murmur3-finalizer avalanche over uint32 lanes.
# ---------------------------------------------------------------------------

def _fmix32(x: jax.Array) -> jax.Array:
    x = x & _M32
    x = x ^ (x >> 16)
    x = (x * np.uint32(0x85EBCA6B)) & _M32
    x = x ^ (x >> 13)
    x = (x * np.uint32(0xC2B2AE35)) & _M32
    x = x ^ (x >> 16)
    return x


def fold_u32(data: jax.Array, dtype: str,
             dictionary: Optional[np.ndarray] = None) -> jax.Array:
    """Value-stable fold of a column into pre-avalanche uint32 words.

    This is the 64→32-bit (and string→crc) part of hash32_values, split out
    so the Pallas fused hash+bucket kernel (pallas_kernels.fused_hash_bucket)
    can consume the same fold and produce bit-identical hashes: the kernel
    applies the murmur finalizer to exactly these words.
    """
    if dtype == STRING:
        if dictionary is None:
            raise HyperspaceException("hash32 of string column requires dictionary")
        host_hashes = np.array(
            [zlib.crc32(s.encode("utf-8")) for s in dictionary], dtype=np.uint32) \
            if len(dictionary) else np.zeros(1, np.uint32)
        table = jnp.asarray(host_hashes)
        codes = jnp.clip(data, 0, max(len(dictionary) - 1, 0))
        return jnp.take(table, codes)
    if dtype in (INT32, DATE, BOOL):
        return data.astype(jnp.uint32)
    if dtype == INT64:
        u = data.astype(jnp.uint64)
        lo = (u & np.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (u >> np.uint64(32)).astype(jnp.uint32)
        return lo ^ (hi * np.uint32(0x9E3779B9))
    if dtype == FLOAT32:
        return jax.lax.bitcast_convert_type(data, jnp.uint32)
    if dtype == FLOAT64:
        bits = jax.lax.bitcast_convert_type(data, jnp.uint64)
        lo = (bits & np.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (bits >> np.uint64(32)).astype(jnp.uint32)
        return lo ^ (hi * np.uint32(0x9E3779B9))
    raise HyperspaceException(f"Cannot hash dtype {dtype}")


def hash32_values(data: jax.Array, dtype: str,
                  dictionary: Optional[np.ndarray] = None) -> jax.Array:
    """Stable 32-bit hash of a column's *values* (not its encoding).

    For strings the hash is computed from the dictionary entries' bytes on
    host (crc32) and gathered by code on device — so two tables with
    different dictionaries hash equal strings equally, which is what makes
    bucket co-partitioning work across index/source/appended data.
    """
    return _fmix32(fold_u32(data, dtype, dictionary))


def _fmix32_host(x: int) -> int:
    """Host mirror of _fmix32 for single literals (bucket pruning)."""
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & 0xFFFFFFFF
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & 0xFFFFFFFF
    x ^= x >> 16
    return x


def hash_combine_host(h1: int, h2: int) -> int:
    """Host mirror of hash_combine."""
    return (h1 ^ ((h2 + 0x9E3779B9 + ((h1 << 6) & 0xFFFFFFFF) + (h1 >> 2))
                  & 0xFFFFFFFF)) & 0xFFFFFFFF


def hash32_value_host(value, dtype: str) -> int:
    """Host-side hash of one literal, identical to hash32_values on device.
    Used to compute the bucket a literal lands in (bucket pruning)."""
    import struct

    if dtype == STRING:
        return _fmix32_host(zlib.crc32(str(value).encode("utf-8")))
    if dtype in (INT32, DATE, BOOL):
        return _fmix32_host(int(value) & 0xFFFFFFFF)
    if dtype == INT64:
        u = int(value) & 0xFFFFFFFFFFFFFFFF
        lo, hi = u & 0xFFFFFFFF, u >> 32
        return _fmix32_host((lo ^ ((hi * 0x9E3779B9) & 0xFFFFFFFF)) & 0xFFFFFFFF)
    if dtype == FLOAT32:
        bits = struct.unpack("<I", struct.pack("<f", float(value)))[0]
        return _fmix32_host(bits)
    if dtype == FLOAT64:
        bits = struct.unpack("<Q", struct.pack("<d", float(value)))[0]
        lo, hi = bits & 0xFFFFFFFF, bits >> 32
        return _fmix32_host((lo ^ ((hi * 0x9E3779B9) & 0xFFFFFFFF)) & 0xFFFFFFFF)
    raise HyperspaceException(f"Cannot hash dtype {dtype}")


def hash_combine(h1: jax.Array, h2: jax.Array) -> jax.Array:
    """Boost-style combiner over uint32."""
    return (h1 ^ ((h2 + np.uint32(0x9E3779B9) + (h1 << 6) + (h1 >> 2)) & _M32)) & _M32


def bucket_ids(hashes: jax.Array, num_buckets: int) -> jax.Array:
    return (hashes % np.uint32(num_buckets)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Sorting.
# ---------------------------------------------------------------------------

def _sort_key_view(data: jax.Array, ascending: bool) -> jax.Array:
    """Transform a key column so ascending lax.sort realizes the requested
    direction (numeric negate; safe for codes/int/float w/o NaN)."""
    if ascending:
        return data
    if data.dtype == jnp.bool_:
        return ~data
    return -data


def lex_sort_indices(keys: Sequence[jax.Array],
                     ascending: Optional[Sequence[bool]] = None) -> jax.Array:
    """Indices that stably sort by keys[0], then keys[1], ... (lexicographic).

    lax.sort sorts by the leading operands; we append iota as the payload.
    """
    if ascending is None:
        ascending = [True] * len(keys)
    n = int(keys[0].shape[0])
    iota = jnp.arange(n, dtype=jnp.int32)
    operands = [_sort_key_view(k, a) for k, a in zip(keys, ascending)] + [iota]
    out = jax.lax.sort(operands, num_keys=len(keys), is_stable=True)
    return out[-1]


# ---------------------------------------------------------------------------
# Merge join over sorted keys.
# ---------------------------------------------------------------------------

def merge_join_indices(left_keys: jax.Array, right_keys_sorted: jax.Array,
                       return_counts: bool = False):
    """Inner equi-join: for each left row, all matching right rows.

    ``right_keys_sorted`` must be ascending. Returns (left_idx, right_idx)
    gather indices — plus the per-left-row match counts when
    ``return_counts`` (outer joins pad count-0 rows). Output length is
    data-dependent → one scalar HOST SYNC.
    """
    lo = jnp.searchsorted(right_keys_sorted, left_keys, side="left")
    hi = jnp.searchsorted(right_keys_sorted, left_keys, side="right")
    counts = (hi - lo).astype(jnp.int32)
    total = int(jnp.sum(counts))  # HOST SYNC (single scalar).
    li, ri = _expand_matches(counts, lo, total)
    if return_counts:
        return li, ri, counts
    return li, ri


@partial(jax.jit, static_argnames=("total",))
def _expand_matches(counts: jax.Array, lo: jax.Array, total: int
                    ) -> Tuple[jax.Array, jax.Array]:
    n_left = counts.shape[0]
    left_idx = jnp.repeat(jnp.arange(n_left, dtype=jnp.int32), counts,
                          total_repeat_length=total)
    starts = jnp.cumsum(counts) - counts
    base = jnp.repeat(starts.astype(jnp.int32), counts, total_repeat_length=total)
    within = jnp.arange(total, dtype=jnp.int32) - base
    right_idx = jnp.repeat(lo.astype(jnp.int32), counts,
                           total_repeat_length=total) + within
    return left_idx, right_idx


def change_mask(sorted_keys: Sequence[jax.Array]) -> jax.Array:
    """True where a row's key tuple differs from the previous row's (rows
    already sorted by the keys); row 0 is False."""
    n = int(sorted_keys[0].shape[0])
    change = jnp.zeros(n, dtype=jnp.bool_)
    for k in sorted_keys:
        change = change | jnp.concatenate(
            [jnp.zeros(1, jnp.bool_), k[1:] != k[:-1]])
    return change


def dense_rank(keys: Sequence[jax.Array]) -> jax.Array:
    """Dense rank of each row's key *tuple* in lexicographic order.

    Equal tuples get equal ranks and ranks are order-preserving, so a
    multi-column equi-join reduces to a single int32-key join on the ranks
    of the two sides' concatenated key columns. Fully on device — no host
    sync (the consumer never needs the rank count).
    """
    n = int(keys[0].shape[0])
    if n == 0:
        return jnp.zeros(0, jnp.int32)
    order = lex_sort_indices(keys)
    change = change_mask([jnp.take(k, order) for k in keys])
    gids = jnp.cumsum(change.astype(jnp.int32))
    return jnp.zeros(n, jnp.int32).at[order].set(gids)


def pack2_int32(a: jax.Array, b: jax.Array) -> jax.Array:
    """Pack two int32 key columns into one int64 composite key.

    ``b`` is sign-biased (XOR 0x80000000) so the packed composite orders the
    same as (a asc, b signed-asc) — without the bias, negative ``b`` values
    sort above positive ones in the low 32 bits and break the merge join's
    sortedness precondition.
    """
    b_biased = (b.astype(jnp.int64) ^ np.int64(0x80000000)) & np.int64(0xFFFFFFFF)
    return (a.astype(jnp.int64) << np.int64(32)) | b_biased


# ---------------------------------------------------------------------------
# Grouping / segmented aggregation (over sorted group keys).
# ---------------------------------------------------------------------------

def group_ids_from_sorted(keys: Sequence[jax.Array]) -> Tuple[jax.Array, int]:
    """Segment ids for rows already sorted by ``keys``.

    Returns (group_id per row, number of groups). One scalar HOST SYNC.
    """
    n = int(keys[0].shape[0])
    if n == 0:
        return jnp.zeros(0, jnp.int32), 0
    gids = jnp.cumsum(change_mask(keys).astype(jnp.int32))
    num_groups = int(gids[-1]) + 1  # HOST SYNC (single scalar).
    return gids, num_groups


def segment_sum(data: jax.Array, gids: jax.Array, num_groups: int) -> jax.Array:
    return jax.ops.segment_sum(data, gids, num_segments=num_groups)


def segment_count(gids: jax.Array, num_groups: int,
                  validity: Optional[jax.Array] = None) -> jax.Array:
    ones = jnp.ones(gids.shape[0], jnp.int64) if validity is None \
        else validity.astype(jnp.int64)
    return jax.ops.segment_sum(ones, gids, num_segments=num_groups)


def segment_min(data: jax.Array, gids: jax.Array, num_groups: int) -> jax.Array:
    return jax.ops.segment_min(data, gids, num_segments=num_groups)


def segment_max(data: jax.Array, gids: jax.Array, num_groups: int) -> jax.Array:
    return jax.ops.segment_max(data, gids, num_segments=num_groups)


def segment_first_index(gids: jax.Array, num_groups: int) -> jax.Array:
    """Index of each group's first row (rows sorted by group key)."""
    n = gids.shape[0]
    return jax.ops.segment_min(jnp.arange(n, dtype=jnp.int32), gids,
                               num_segments=num_groups)


# ---------------------------------------------------------------------------
# Membership (lineage anti-filter: Not(In(lineage, deletedIds))).
# ---------------------------------------------------------------------------

def isin_sorted(data: jax.Array, sorted_values: jax.Array) -> jax.Array:
    """Vectorized membership of ``data`` in ascending ``sorted_values``."""
    if sorted_values.shape[0] == 0:
        return jnp.zeros(data.shape[0], jnp.bool_)
    pos = jnp.searchsorted(sorted_values, data)
    pos = jnp.clip(pos, 0, sorted_values.shape[0] - 1)
    return jnp.take(sorted_values, pos) == data
