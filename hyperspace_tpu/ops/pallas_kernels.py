"""Pallas TPU kernels for the hot single-pass ops.

These fuse the per-row work of the index-build and scan paths into single
HBM-read kernels, where the pure-jnp formulations would each materialize
intermediates (per-column hashes, combined hash, compare masks) in HBM:

- ``fused_hash_bucket``: murmur-finalizer avalanche of every indexed column's
  pre-folded u32 words + boost-combine across columns + mod num_buckets, one
  pass. TPU-native core of the reference's ``repartition(numBuckets, cols)``
  (actions/CreateActionBase.scala:118-121).
- ``fused_compare_mask`` / ``fused_range_mask``: predicate evaluation for
  filter scans — one read of the column, no intermediate compare results.
- ``masked_minmax``: MinMax sketch build (data-skipping) in one reduction
  pass with a validity mask.
- ``bucket_histogram``: per-bucket row counts, used for the bucket boundary
  offsets of the sorted index build.

All kernels operate on 32-bit lanes (int32/uint32/float32); 64-bit columns
are folded to u32 words *outside* the kernel (see kernels.fold_u32) — TPU
VPUs are 32-bit-lane machines and the fold is where 64-bit semantics live.
On non-TPU backends the kernels run in interpret mode (tests) or the caller
falls back to the pure-jnp path (default on CPU: interpret mode is slow).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_LANES = 128
_BLK_ROWS = 256          # (256, 128) i32 block = 128 KiB in VMEM.
_HIST_BLK_ROWS = 32      # histogram materializes a (rows*128, nb) one-hot.

_M32 = np.uint32(0xFFFFFFFF)

# Index-map constants must stay i32: under jax_enable_x64 a bare Python 0 is
# traced as i64, which Mosaic cannot legalize in block index maps.
_Z = np.int32(0)

# ---------------------------------------------------------------------------
# Enablement. "auto" → real kernels on TPU, pure-jnp fallback elsewhere;
# "on" → also on CPU via interpret mode (tests); "off" → never.
# ---------------------------------------------------------------------------

_mode: Optional[str] = None


def set_mode(mode: str) -> None:
    """'auto' | 'on' | 'off' (overrides env HST_PALLAS)."""
    global _mode
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"bad pallas mode {mode!r}")
    _mode = mode


def _get_mode() -> str:
    return _mode if _mode is not None else os.environ.get("HST_PALLAS", "auto")


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def enabled() -> bool:
    mode = _get_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    return _on_tpu()


def _interpret() -> bool:
    return not _on_tpu()


# ---------------------------------------------------------------------------
# Shape plumbing: 1-D column -> padded (rows, 128) tiles and back.
# ---------------------------------------------------------------------------

def _pad_2d(x: jax.Array, blk_rows: int, fill) -> Tuple[jax.Array, int]:
    """Pad a 1-D array to a multiple of blk_rows*128 and reshape to
    (rows, 128). Returns (tiles, original length)."""
    n = x.shape[0]
    chunk = blk_rows * _LANES
    padded = max(((n + chunk - 1) // chunk) * chunk, chunk)
    if padded != n:
        x = jnp.concatenate(
            [x, jnp.full(padded - n, fill, dtype=x.dtype)])
    return x.reshape(-1, _LANES), n


def _unpad(tiles: jax.Array, n: int) -> jax.Array:
    return tiles.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# fused hash + bucket id.
# ---------------------------------------------------------------------------

def _fmix32(x):
    x = x ^ (x >> 16)
    x = x * np.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * np.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _hash_bucket_kernel(*refs, ncols: int, num_buckets: int):
    word_refs, hash_ref, bid_ref = refs[:ncols], refs[ncols], refs[ncols + 1]
    h = _fmix32(word_refs[0][:])
    for c in range(1, ncols):
        hc = _fmix32(word_refs[c][:])
        # boost hash_combine (kernels.hash_combine semantics).
        h = h ^ (hc + np.uint32(0x9E3779B9) + (h << 6) + (h >> 2))
    hash_ref[:] = h
    bid_ref[:] = (h % np.uint32(num_buckets)).astype(jnp.int32)


def fused_hash_bucket(folded: Sequence[jax.Array], num_buckets: int
                      ) -> Tuple[jax.Array, jax.Array]:
    """One-pass (combined hash, bucket id) from pre-folded u32 columns.

    ``folded[c]`` is column c's value-stable u32 fold (kernels.fold_u32);
    results match kernels.hash32_values + hash_combine + bucket_ids exactly.
    Each column is its own input ref (no stacked copy in HBM).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ncols = len(folded)
    n = folded[0].shape[0]
    tiles = [_pad_2d(f.astype(jnp.uint32), _BLK_ROWS, 0)[0] for f in folded]
    rows = tiles[0].shape[0]
    grid = (rows // _BLK_ROWS,)

    hashes, bids = pl.pallas_call(
        partial(_hash_bucket_kernel, ncols=ncols, num_buckets=num_buckets),
        grid=grid,
        in_specs=[pl.BlockSpec((_BLK_ROWS, _LANES), lambda i: (i, _Z),
                               memory_space=pltpu.VMEM)] * ncols,
        out_specs=[
            pl.BlockSpec((_BLK_ROWS, _LANES), lambda i: (i, _Z),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLK_ROWS, _LANES), lambda i: (i, _Z),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANES), jnp.uint32),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        ],
        interpret=_interpret(),
    )(*tiles)
    return _unpad(hashes, n), _unpad(bids, n)


# ---------------------------------------------------------------------------
# fused predicate masks.
# ---------------------------------------------------------------------------

_OPS = ("==", "!=", "<", "<=", ">", ">=")


def _compare_kernel(x_ref, lit_ref, out_ref, *, op: str):
    x = x_ref[:]
    v = lit_ref[0, 0]
    if op == "==":
        m = x == v
    elif op == "!=":
        m = x != v
    elif op == "<":
        m = x < v
    elif op == "<=":
        m = x <= v
    elif op == ">":
        m = x > v
    else:
        m = x >= v
    out_ref[:] = m


def fused_compare_mask(x: jax.Array, op: str, value) -> jax.Array:
    """Elementwise ``x <op> value`` mask in one pass (32-bit dtypes)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if op not in _OPS:
        raise ValueError(f"bad op {op!r}")
    tiles, n = _pad_2d(x, _BLK_ROWS, 0)
    rows = tiles.shape[0]
    lit = jnp.array([[value]], dtype=x.dtype)
    out = pl.pallas_call(
        partial(_compare_kernel, op=op),
        grid=(rows // _BLK_ROWS,),
        in_specs=[
            pl.BlockSpec((_BLK_ROWS, _LANES), lambda i: (i, _Z),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (_Z, _Z),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((_BLK_ROWS, _LANES), lambda i: (i, _Z),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.bool_),
        interpret=_interpret(),
    )(tiles, lit)
    return _unpad(out, n)


def _range_kernel(x_ref, lo_ref, hi_ref, out_ref, *, lo_incl: bool,
                  hi_incl: bool):
    x = x_ref[:]
    lo, hi = lo_ref[0, 0], hi_ref[0, 0]
    ml = (x >= lo) if lo_incl else (x > lo)
    mh = (x <= hi) if hi_incl else (x < hi)
    out_ref[:] = ml & mh


def fused_range_mask(x: jax.Array, lo, hi, lo_incl: bool = True,
                     hi_incl: bool = True) -> jax.Array:
    """``lo <(=) x <(=) hi`` in one pass — the BETWEEN hot path."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tiles, n = _pad_2d(x, _BLK_ROWS, 0)
    rows = tiles.shape[0]
    lo_a = jnp.array([[lo]], dtype=x.dtype)
    hi_a = jnp.array([[hi]], dtype=x.dtype)
    out = pl.pallas_call(
        partial(_range_kernel, lo_incl=lo_incl, hi_incl=hi_incl),
        grid=(rows // _BLK_ROWS,),
        in_specs=[
            pl.BlockSpec((_BLK_ROWS, _LANES), lambda i: (i, _Z),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (_Z, _Z), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (_Z, _Z), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((_BLK_ROWS, _LANES), lambda i: (i, _Z),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.bool_),
        interpret=_interpret(),
    )(tiles, lo_a, hi_a)
    return _unpad(out, n)


# ---------------------------------------------------------------------------
# masked min/max reduction (MinMax sketch build).
# ---------------------------------------------------------------------------

def _minmax_kernel(x_ref, valid_ref, min_ref, max_ref, *, lo_sent, hi_sent):
    import jax.experimental.pallas as pl

    step = pl.program_id(0)
    x = x_ref[:]
    v = valid_ref[:]
    blk_min = jnp.min(jnp.where(v, x, hi_sent))
    blk_max = jnp.max(jnp.where(v, x, lo_sent))

    @pl.when(step == 0)
    def _():
        min_ref[0, 0] = blk_min
        max_ref[0, 0] = blk_max

    @pl.when(step != 0)
    def _():
        min_ref[0, 0] = jnp.minimum(min_ref[0, 0], blk_min)
        max_ref[0, 0] = jnp.maximum(max_ref[0, 0], blk_max)


def _minmax_nomask_kernel(x_ref, n_ref, min_ref, max_ref, *, lo_sent,
                          hi_sent):
    import jax.experimental.pallas as pl

    step = pl.program_id(0)
    x = x_ref[:]
    # Validity derived in-kernel from the global lane index (no mask array
    # streamed from HBM): only the padded tail is invalid.
    base = step * np.int32(_BLK_ROWS * _LANES)
    ridx = jax.lax.broadcasted_iota(jnp.int32, (_BLK_ROWS, _LANES), 0)
    lidx = jax.lax.broadcasted_iota(jnp.int32, (_BLK_ROWS, _LANES), 1)
    v = (base + ridx * np.int32(_LANES) + lidx) < n_ref[0, 0]
    blk_min = jnp.min(jnp.where(v, x, hi_sent))
    blk_max = jnp.max(jnp.where(v, x, lo_sent))

    @pl.when(step == 0)
    def _():
        min_ref[0, 0] = blk_min
        max_ref[0, 0] = blk_max

    @pl.when(step != 0)
    def _():
        min_ref[0, 0] = jnp.minimum(min_ref[0, 0], blk_min)
        max_ref[0, 0] = jnp.maximum(max_ref[0, 0], blk_max)


def masked_minmax(x: jax.Array, valid: Optional[jax.Array] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """(min, max) over valid lanes in one pass. Returns device scalars;
    all-invalid input yields (dtype max, dtype min) sentinels.

    With ``valid=None`` (no nulls — the common sketch-build case) no mask
    array is streamed: tail validity is computed from lane indices in-kernel.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if jnp.issubdtype(x.dtype, jnp.floating):
        info = jnp.finfo(x.dtype)
    else:
        info = jnp.iinfo(x.dtype)
    lo_sent = np.asarray(info.min, dtype=x.dtype)
    hi_sent = np.asarray(info.max, dtype=x.dtype)

    n = x.shape[0]
    tiles, _ = _pad_2d(x, _BLK_ROWS, hi_sent)
    rows = tiles.shape[0]
    scalar_out = [
        pl.BlockSpec((1, 1), lambda i: (_Z, _Z), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1), lambda i: (_Z, _Z), memory_space=pltpu.SMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((1, 1), x.dtype),
        jax.ShapeDtypeStruct((1, 1), x.dtype),
    ]
    if valid is None:
        mn, mx = pl.pallas_call(
            partial(_minmax_nomask_kernel, lo_sent=lo_sent, hi_sent=hi_sent),
            grid=(rows // _BLK_ROWS,),
            in_specs=[
                pl.BlockSpec((_BLK_ROWS, _LANES), lambda i: (i, _Z),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), lambda i: (_Z, _Z),
                             memory_space=pltpu.SMEM),
            ],
            out_specs=scalar_out,
            out_shape=out_shape,
            interpret=_interpret(),
        )(tiles, jnp.array([[n]], dtype=jnp.int32))
        return mn[0, 0], mx[0, 0]
    vtiles, _ = _pad_2d(valid, _BLK_ROWS, False)
    mn, mx = pl.pallas_call(
        partial(_minmax_kernel, lo_sent=lo_sent, hi_sent=hi_sent),
        grid=(rows // _BLK_ROWS,),
        in_specs=[
            pl.BlockSpec((_BLK_ROWS, _LANES), lambda i: (i, _Z),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLK_ROWS, _LANES), lambda i: (i, _Z),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=scalar_out,
        out_shape=out_shape,
        interpret=_interpret(),
    )(tiles, vtiles)
    return mn[0, 0], mx[0, 0]


# ---------------------------------------------------------------------------
# bucket histogram (radix-partition planning).
# ---------------------------------------------------------------------------

def _hist_kernel(bid_ref, out_ref, *, num_buckets: int):
    import jax.experimental.pallas as pl

    step = pl.program_id(0)
    bids = bid_ref[:]

    # At step 0 the output block is uninitialized; multiply the previous
    # value by 0 instead of branching (lax.cond over ref reads recurses in
    # the Mosaic lowering).
    keep = jnp.where(step == 0, jnp.int32(0), jnp.int32(1))
    one = jnp.ones(bids.shape, jnp.float32)
    zero = jnp.zeros(bids.shape, jnp.float32)

    def body(b, _):
        # f32 accumulator: integer jnp.sum promotes through int64 under
        # jax_enable_x64, which Mosaic cannot lower; f32 is exact for block
        # counts (block ≤ 2^24 lanes).
        cnt = jnp.sum(jnp.where(bids == b, one, zero)).astype(jnp.int32)
        out_ref[0, b] = out_ref[0, b] * keep + cnt
        return jnp.int32(0)

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(num_buckets), body,
                      jnp.int32(0))


def bucket_histogram(bids: jax.Array, num_buckets: int) -> jax.Array:
    """Row count per bucket id. bids: int32[n] in [0, num_buckets)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tiles, _ = _pad_2d(bids.astype(jnp.int32), _HIST_BLK_ROWS,
                       np.int32(-1))  # -1 matches no bucket.
    rows = tiles.shape[0]
    out = pl.pallas_call(
        partial(_hist_kernel, num_buckets=num_buckets),
        grid=(rows // _HIST_BLK_ROWS,),
        in_specs=[pl.BlockSpec((_HIST_BLK_ROWS, _LANES), lambda i: (i, _Z),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, num_buckets), lambda i: (_Z, _Z),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, num_buckets), jnp.int32),
        interpret=_interpret(),
    )(tiles)
    return out[0]


# ---------------------------------------------------------------------------
# On-device self-check. Verifies each kernel compiles under Mosaic on the
# live backend AND matches the pure-jnp reference numerics; on any failure
# the module auto-disables (set_mode("off")) so product paths silently use
# the jnp fallbacks. Run by bench.py at startup (VERDICT r1 item #1) and
# available to users as hyperspace_tpu.ops.pallas_kernels.self_check().
# ---------------------------------------------------------------------------

def self_check(n: int = 4096, auto_disable: bool = True) -> dict:
    """Run every Pallas kernel against its jnp reference on the current
    default backend. Returns {kernel_name: "ok" | "FAIL: <err>"} plus
    {"_enabled": bool} reflecting the post-check mode. Never raises."""
    from . import kernels as K

    results: dict = {}
    if not enabled():
        results["_enabled"] = False
        results["_note"] = "pallas disabled (mode=%s, backend=%s)" % (
            _get_mode(), jax.default_backend())
        return results

    rng = np.random.default_rng(7)
    ok = True

    def run(name, fn):
        nonlocal ok
        try:
            err = fn()
            results[name] = "ok" if err is None else f"FAIL: {err}"
            ok = ok and err is None
        except Exception as e:  # compile/runtime failure on this backend
            results[name] = f"FAIL: {type(e).__name__}: {e}"
            ok = False

    def chk_hash_bucket():
        cols = [jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32))
                for _ in range(2)]
        h, b = fused_hash_bucket(cols, 32)
        ref_h = K._fmix32(cols[0])
        ref_h = K.hash_combine(ref_h, K._fmix32(cols[1]))
        ref_b = (ref_h % np.uint32(32)).astype(jnp.int32)
        if not (np.array_equal(np.asarray(h), np.asarray(ref_h))
                and np.array_equal(np.asarray(b), np.asarray(ref_b))):
            return "hash/bucket mismatch vs jnp reference"

    def chk_range_mask():
        x = jnp.asarray(rng.integers(-1000, 1000, n, dtype=np.int32))
        m = fused_range_mask(x, -50, 310, True, False)
        ref = (x >= -50) & (x < 310)
        if not np.array_equal(np.asarray(m), np.asarray(ref)):
            return "range mask mismatch"

    def chk_compare_mask():
        x = jnp.asarray(rng.integers(-1000, 1000, n, dtype=np.int32))
        for op, ref in (("==", x == 3), ("<", x < 3), (">=", x >= 3)):
            m = fused_compare_mask(x, op, 3)
            if not np.array_equal(np.asarray(m), np.asarray(ref)):
                return f"compare mask mismatch for {op}"

    def chk_minmax():
        x = jnp.asarray(rng.integers(-10**6, 10**6, n, dtype=np.int32))
        mn, mx = masked_minmax(x)
        if int(mn) != int(x.min()) or int(mx) != int(x.max()):
            return "minmax (no mask) mismatch"
        v = jnp.asarray(rng.random(n) < 0.5)
        mn, mx = masked_minmax(x, v)
        xs = np.asarray(x)[np.asarray(v)]
        if int(mn) != int(xs.min()) or int(mx) != int(xs.max()):
            return "minmax (masked) mismatch"

    def chk_histogram():
        b = jnp.asarray(rng.integers(0, 32, n, dtype=np.int32))
        h = bucket_histogram(b, 32)
        ref = np.bincount(np.asarray(b), minlength=32)
        if not np.array_equal(np.asarray(h), ref):
            return "histogram mismatch"

    run("fused_hash_bucket", chk_hash_bucket)
    run("fused_range_mask", chk_range_mask)
    run("fused_compare_mask", chk_compare_mask)
    run("masked_minmax", chk_minmax)
    run("bucket_histogram", chk_histogram)

    if not ok and auto_disable:
        set_mode("off")
    results["_enabled"] = enabled()
    return results
