"""Covering-index build pipeline: hash-partition + sort-within-bucket on device.

This is the TPU-native replacement for the reference's index-creation Spark
job — ``df.repartition(numBuckets, indexedCols)`` followed by a bucketed,
sorted write (reference: actions/CreateActionBase.scala:111-181,
index/DataFrameWriterExtensions.scala:50-68). Instead of a network shuffle,
the whole dataset is bucket-assigned with a murmur-style hash and sorted by
(bucket, indexed columns) in one fused XLA program; the distributed variant
(parallel/distributed_build.py) shards rows over the mesh and exchanges
buckets with an all-to-all over ICI.

The single-scalar host reads here are bucket boundaries, needed to slice the
sorted array into per-bucket parquet files at the host DMA boundary.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException
from ..execution.columnar import Table
from . import kernels


def bucket_ids_for(table: Table, indexed_cols: Sequence[str],
                   num_buckets: int) -> jax.Array:
    """Bucket id per row: combined value-stable hash of the indexed columns
    modulo num_buckets (parity with the repartition-by-key semantics).

    On TPU the fold→avalanche→combine→mod chain runs as one fused Pallas
    kernel (single HBM pass over all indexed columns); the jnp fallback is
    semantically identical.
    """
    from . import pallas_kernels

    if pallas_kernels.enabled():
        folded = []
        for name in indexed_cols:
            col = table.column(name)
            folded.append(kernels.fold_u32(col.data, col.dtype, col.dictionary))
        _, bids = pallas_kernels.fused_hash_bucket(folded, num_buckets)
        return bids
    h = None
    for name in indexed_cols:
        col = table.column(name)
        ch = kernels.hash32_values(col.data, col.dtype, col.dictionary)
        h = ch if h is None else kernels.hash_combine(h, ch)
    return kernels.bucket_ids(h, num_buckets)


def build_sorted_buckets(table: Table, indexed_cols: Sequence[str],
                         num_buckets: int) -> Tuple[Table, np.ndarray]:
    """Sort all rows by (bucket id, indexed columns); return the sorted table
    and per-bucket boundary offsets (len num_buckets+1, host numpy).

    Rows within each bucket end up sorted by the indexed columns — exactly
    the invariant the shuffle-free merge join and bucket-pruned filter scan
    rely on.
    """
    from . import pallas_kernels

    bids = bucket_ids_for(table, indexed_cols, num_buckets)
    sort_keys = [bids] + [table.column(c).data for c in indexed_cols]
    perm = kernels.lex_sort_indices(sort_keys)
    sorted_table = table.take(perm)
    if pallas_kernels.enabled():
        # Boundary offsets from the per-bucket histogram (one pass over the
        # unsorted bids) instead of a searchsorted over the sorted copy.
        counts = pallas_kernels.bucket_histogram(bids, num_buckets)
        boundaries = jnp.concatenate(
            [jnp.zeros(1, counts.dtype), jnp.cumsum(counts)])
    else:
        sorted_bids = jnp.take(bids, perm)
        boundaries = jnp.searchsorted(
            sorted_bids, jnp.arange(num_buckets + 1, dtype=sorted_bids.dtype))
    return sorted_table, np.asarray(jax.device_get(boundaries))


def bucket_file_name(bucket: int) -> str:
    """One file per bucket (bucket id recoverable from the name, mirroring
    Spark's BucketingUtils suffix convention)."""
    return f"part-{bucket:05d}.parquet"


def bucket_id_from_file(path: str) -> Optional[int]:
    import os
    import re
    m = re.match(r"part-(\d{5})", os.path.basename(path))
    return int(m.group(1)) if m else None
