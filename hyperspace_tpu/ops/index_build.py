"""Covering-index build pipeline: hash-partition + sort-within-bucket on device.

This is the TPU-native replacement for the reference's index-creation Spark
job — ``df.repartition(numBuckets, indexedCols)`` followed by a bucketed,
sorted write (reference: actions/CreateActionBase.scala:111-181,
index/DataFrameWriterExtensions.scala:50-68). Instead of a network shuffle,
the whole dataset is bucket-assigned with a murmur-style hash and sorted by
(bucket, indexed columns) in one fused XLA program; the distributed variant
(parallel/distributed_build.py) shards rows over the mesh and exchanges
buckets with an all-to-all over ICI.

The single-scalar host reads here are bucket boundaries, needed to slice the
sorted array into per-bucket parquet files at the host DMA boundary.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..execution.columnar import Table
from ..index import data_store
from . import kernels


def bucket_ids_for(table: Table, indexed_cols: Sequence[str],
                   num_buckets: int) -> jax.Array:
    """Bucket id per row: combined value-stable hash of the indexed columns
    modulo num_buckets (parity with the repartition-by-key semantics).

    On TPU the fold→avalanche→combine→mod chain runs as one fused Pallas
    kernel (single HBM pass over all indexed columns); the jnp fallback is
    semantically identical.
    """
    from . import pallas_kernels

    if pallas_kernels.enabled():
        folded = []
        for name in indexed_cols:
            col = table.column(name)
            folded.append(kernels.fold_u32(col.data, col.dtype, col.dictionary))
        _, bids = pallas_kernels.fused_hash_bucket(folded, num_buckets)
        return bids
    h = None
    for name in indexed_cols:
        col = table.column(name)
        ch = kernels.hash32_values(col.data, col.dtype, col.dictionary)
        h = ch if h is None else kernels.hash_combine(h, ch)
    return kernels.bucket_ids(h, num_buckets)


def build_sorted_buckets(table: Table, indexed_cols: Sequence[str],
                         num_buckets: int) -> Tuple[Table, np.ndarray]:
    """Sort all rows by (bucket id, indexed columns); return the sorted table
    and per-bucket boundary offsets (len num_buckets+1, host numpy).

    Rows within each bucket end up sorted by the indexed columns — exactly
    the invariant the shuffle-free merge join and bucket-pruned filter scan
    rely on.
    """
    from . import pallas_kernels

    bids = bucket_ids_for(table, indexed_cols, num_buckets)
    sort_keys = [bids] + [table.column(c).data for c in indexed_cols]
    # pad=False: the build sorts the whole dataset at a stable length —
    # class padding would cost ~growthFactor/2 extra sort work per build
    # for no compile reuse (the length only changes when the data does).
    perm = kernels.lex_sort_indices(sort_keys, pad=False)
    sorted_table = table.take(perm)
    if pallas_kernels.enabled():
        # Boundary offsets from the per-bucket histogram (one pass over the
        # unsorted bids) instead of a searchsorted over the sorted copy.
        counts = pallas_kernels.bucket_histogram(bids, num_buckets)
        boundaries = jnp.concatenate(
            [jnp.zeros(1, counts.dtype), jnp.cumsum(counts)])
    else:
        sorted_bids = jnp.take(bids, perm)
        boundaries = jnp.searchsorted(
            sorted_bids, jnp.arange(num_buckets + 1, dtype=sorted_bids.dtype))
    return sorted_table, np.asarray(jax.device_get(boundaries))


# Chunked-build observability: tests pin the device-footprint cap by
# asserting max_device_rows never exceeded the configured chunk budget
# (SURVEY §7 hard-part #1: the build must stream, not materialize).
CHUNK_STATS = {"max_device_rows": 0, "chunks": 0, "spill_bytes": 0}
# Concurrent actions can build indexes in parallel (serving-path
# refresh/optimize); every write goes through the helpers under the
# lock — an unguarded max()+assign or += loses updates under contention
# (HS301/HS302, scripts/analysis).
_CHUNK_STATS_LOCK = threading.Lock()


def _note_device_rows(n: int) -> None:
    with _CHUNK_STATS_LOCK:
        CHUNK_STATS["max_device_rows"] = max(
            CHUNK_STATS["max_device_rows"], n)


def _bump_chunk_stat(key: str, delta: int) -> None:
    with _CHUNK_STATS_LOCK:
        CHUNK_STATS[key] += delta


def build_sorted_buckets_chunked(
        files: Sequence[str], columns: Sequence[str],
        indexed_cols: Sequence[str], num_buckets: int, chunk_rows: int,
        out_dir: str, row_group_size: int,
        lineage_ids: Optional[Sequence[int]] = None,
        lineage_col: Optional[str] = None) -> None:
    """Streaming covering-index build for data larger than HBM.

    Pipeline per chunk (≤ ``chunk_rows`` rows resident on device at once):
    hash+bucket-sort the chunk (one XLA program, same kernel as the
    in-memory build), DMA to host, and append each bucket's slice as a row
    group to that bucket's SPILL FILE on disk. After the stream: per
    bucket, read its spill back, re-sort on device (bucket size ≪ dataset
    size), write the final parquet — the identical one-file-per-bucket
    layout and within-bucket order the in-memory path produces
    (actions/create.py layout rule).

    The reference achieves the same scale via Spark's external shuffle
    (CreateActionBase.scala:111-121); here the host filesystem genuinely
    plays the shuffle-spill role — host RAM holds one chunk (plus write
    buffers) and the device one chunk or one bucket at a time.
    """
    import shutil
    import tempfile

    # NOT under out_dir: the version dir is named "v__=<n>", and pyarrow's
    # dataset reader would hive-infer a phantom "v__" column from any file
    # path inside it. Removed even on failure (it can hold dataset-scale
    # bytes).
    spill_dir = tempfile.mkdtemp(prefix="hs_build_spill_")
    try:
        _chunked_spill_and_merge(
            files, columns, indexed_cols, num_buckets, chunk_rows, out_dir,
            row_group_size, lineage_ids, lineage_col, spill_dir)
    finally:
        shutil.rmtree(spill_dir, ignore_errors=True)


def _chunked_spill_and_merge(files, columns, indexed_cols, num_buckets,
                             chunk_rows, out_dir, row_group_size,
                             lineage_ids, lineage_col,
                             spill_dir: str) -> None:
    import os

    import pyarrow.parquet as pq

    from ..execution.columnar import (Column, iter_parquet_chunks,
                                      parquet_row_counts, read_parquet)
    from ..schema import INT64

    writers: Dict[int, pq.ParquetWriter] = {}
    try:
        for chunk, provenance in iter_parquet_chunks(files, columns,
                                                     chunk_rows):
            if lineage_ids is not None:
                ids = np.concatenate([
                    np.full(cnt, lineage_ids[fi], np.int64)
                    for fi, cnt in provenance])
                chunk = chunk.with_column(lineage_col,
                                          Column(INT64, jnp.asarray(ids)))
            _note_device_rows(chunk.num_rows)
            _bump_chunk_stat("chunks", 1)
            sorted_chunk, bounds = build_sorted_buckets(
                chunk, indexed_cols, num_buckets)
            at = sorted_chunk.to_arrow()
            for b in range(num_buckets):
                lo, hi = int(bounds[b]), int(bounds[b + 1])
                if hi <= lo:
                    continue
                run = at.slice(lo, hi - lo)
                _bump_chunk_stat("spill_bytes", run.nbytes)
                w = writers.get(b)
                if w is None:
                    w = pq.ParquetWriter(
                        os.path.join(spill_dir, f"bucket{b:05d}.parquet"),
                        run.schema)
                    writers[b] = w
                w.write_table(run)
    finally:
        for w in writers.values():
            w.close()

    # Final merge, BATCHED: one device sort per batch of buckets instead
    # of one per bucket. 200 default buckets mean 200 tiny sorts + 200
    # host↔device round trips the per-bucket loop paid — the measured
    # build-throughput decline at scale (369k rows/s @SF5 → 200k @SF50)
    # is dominated by this fan-in. Batches pack whole buckets up to the
    # device chunk budget, sort once by (bucket, keys), and slice each
    # bucket's run back out; per-bucket files and within-bucket order are
    # byte-identical to the per-bucket loop's.
    bucket_list = sorted(writers)
    spill_paths = {b: os.path.join(spill_dir, f"bucket{b:05d}.parquet")
                   for b in bucket_list}
    rows_of = dict(zip(bucket_list,
                       parquet_row_counts([spill_paths[b]
                                           for b in bucket_list])))

    batches: List[List[int]] = []
    batch: List[int] = []
    batch_rows = 0
    for b in bucket_list:
        if batch and batch_rows + rows_of[b] > chunk_rows:
            batches.append(batch)
            batch, batch_rows = [], 0
        batch.append(b)
        batch_rows += rows_of[b]
    if batch:
        batches.append(batch)

    def _read_batch(batch):
        # One multi-file read (host-side dictionary unification, file
        # order preserved) — not a per-file read + device concat, which
        # would hold ~3x the batch on device at the merge peak.
        return read_parquet([spill_paths[b] for b in batch])

    def _batch_weight(batch) -> int:
        try:
            return sum(os.path.getsize(spill_paths[b]) for b in batch)
        except OSError:
            return 0

    # Double-buffered merge (parallel/io.py): batch i+1 reads back from
    # spill while batch i sorts on device and writes its bucket files.
    # Residency is pinned to TWO batches alive (threads=2, depth=0 →
    # one in-flight read + the one being consumed) — each batch is
    # ~chunk_rows decoded device rows, so the pool's general
    # threads+prefetchDepth window would multiply the device footprint
    # the chunked build exists to bound.
    from ..parallel import io as pio
    p = pio.active_params()
    merge_params = pio.IoParams(
        enabled=p.enabled, threads=min(2, p.resolved_threads()),
        prefetch_depth=0, max_inflight_bytes=p.max_inflight_bytes)
    for batch, merged in pio.zip_prefetch(
            batches, _read_batch, weight=_batch_weight,
            params=merge_params, label="spill_merge"):
        bids = np.concatenate([np.full(rows_of[b], i, np.int32)
                               for i, b in enumerate(batch)])
        _note_device_rows(merged.num_rows)
        keys = [jnp.asarray(bids)] + \
            [merged.column(c).data for c in indexed_cols]
        perm = kernels.lex_sort_indices(keys)
        merged = merged.take(perm)
        at = merged.to_arrow()
        lo = 0
        for i, b in enumerate(batch):
            hi = lo + rows_of[b]
            _dst = os.path.join(out_dir, bucket_file_name(b))
            _fs, _dstp = data_store.fs_and_path(_dst)
            pq.write_table(at.slice(lo, hi - lo), _dstp,
                           row_group_size=row_group_size, filesystem=_fs)
            lo = hi


def bucket_file_name(bucket: int) -> str:
    """One file per bucket (bucket id recoverable from the name, mirroring
    Spark's BucketingUtils suffix convention)."""
    return f"part-{bucket:05d}.parquet"


def bucket_id_from_file(path: str) -> Optional[int]:
    import os
    import re
    m = re.match(r"part-(\d{5})", os.path.basename(path))
    return int(m.group(1)) if m else None
