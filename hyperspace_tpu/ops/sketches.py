"""Data-skipping sketch builders: per-source-file MinMax and BloomFilter.

Capability note: sketch-based data skipping does not exist in the mounted
reference snapshot (SURVEY.md version note — `DataSkippingIndex` landed in
later Hyperspace versions); it is a target capability per BASELINE.json.
The design slots into the reference's metadata model exactly where its
`derivedDataset.kind` field anticipates it (index/IndexLogEntry.scala:349).

TPU-native: both sketches are built as one-pass device reductions over each
file's column — min/max via jnp reductions, bloom membership via the same
murmur-style value hash the bucket exchange uses (ops/kernels.py) with
double hashing to derive k probe positions, scattered into a bit array on
device. Probing at plan time is host-side (one literal vs a few thousand
sketch rows — no device roundtrip is worth it).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException
from ..execution import shapes
from ..execution.columnar import Column
from ..schema import DATE, STRING
from . import kernels

# Second hash for double hashing: mix of the first with a golden-ratio salt
# (device and host mirrors must match bit-for-bit).
_SALT = 0x9E3779B9


def _h2_device(h1: jax.Array) -> jax.Array:
    return kernels._fmix32(h1 ^ np.uint32(_SALT))


def _h2_host(h1: int) -> int:
    return kernels._fmix32_host(h1 ^ _SALT)


def bloom_parameters(expected_items: int, fpp: float) -> Tuple[int, int]:
    """Classic (num_bits, num_hashes) sizing for a target false-positive
    rate. Bits are rounded up to a byte multiple for packing."""
    if not (0.0 < fpp < 1.0):
        raise HyperspaceException(f"fpp must be in (0, 1); got {fpp}")
    n = max(int(expected_items), 1)
    m = max(8, int(math.ceil(-n * math.log(fpp) / (math.log(2) ** 2))))
    m = ((m + 7) // 8) * 8
    k = max(1, int(round(m / n * math.log(2))))
    return m, k


def bloom_build(col: Column, num_bits: int, num_hashes: int) -> np.ndarray:
    """Build a bloom bitset over the column's valid values on device.
    Returns the packed bits as host uint8 (num_bits/8 bytes).

    Shape classes: the column is padded to its length class so every
    per-file build at a class shares one compiled program; pad rows (like
    null rows) scatter onto the overflow bit that is sliced away — the
    packed bitset is byte-identical to the unpadded build."""
    data, n = shapes.pad_class(col.data)
    validity = col.validity
    if validity is not None:
        validity = shapes.pad_to(validity, int(data.shape[0]), False)
    elif shapes.is_padded(data, n):
        validity = shapes.valid_mask(int(data.shape[0]), n)
    h1 = kernels.hash32_values(data, col.dtype, col.dictionary)
    h2 = _h2_device(h1)
    i = jnp.arange(num_hashes, dtype=jnp.uint32)[:, None]
    pos = ((h1[None, :] + i * h2[None, :]) % np.uint32(num_bits)).astype(jnp.int32)
    if validity is not None:
        # Null (and pad) rows scatter onto an overflow bit, sliced away.
        pos = jnp.where(validity[None, :], pos, num_bits)
    bits = jnp.zeros(num_bits + 1, jnp.bool_).at[pos.reshape(-1)].set(True)
    return np.packbits(np.asarray(jax.device_get(bits[:num_bits])))


def bloom_might_contain(packed: np.ndarray, value, dtype: str,
                        num_bits: int, num_hashes: int) -> bool:
    """Host-side membership probe for one literal (mirrors bloom_build)."""
    h1 = kernels.hash32_value_host(value, dtype)
    h2 = _h2_host(h1)
    bits = np.unpackbits(np.frombuffer(packed, dtype=np.uint8),
                         count=num_bits)
    for i in range(num_hashes):
        # Mirror the device's wrapping uint32 arithmetic exactly.
        if not bits[((h1 + i * h2) & 0xFFFFFFFF) % num_bits]:
            return False
    return True


def value_list(col: Column, max_values: int) -> Optional[list]:
    """Sorted distinct valid values of the column as host python objects,
    or None when cardinality exceeds ``max_values`` (the sketch degrades
    to "no information" for that file — it must never prune wrongly).
    Exact equality/IN pruning for low-cardinality categorical columns,
    where MinMax is blunt (scattered values span the whole range)."""
    import datetime

    data = np.asarray(jax.device_get(col.data))
    if col.validity is not None:
        data = data[np.asarray(jax.device_get(col.validity))]
    if data.size == 0:
        return []
    uniq = np.unique(data)
    if uniq.size > max_values:
        return None
    if col.dtype == STRING:
        return [str(col.dictionary[int(c)]) for c in uniq]
    if col.dtype == DATE:
        epoch = datetime.date(1970, 1, 1)
        return [epoch + datetime.timedelta(days=int(d)) for d in uniq]
    return [v.item() for v in uniq]


def minmax_values(col: Column) -> Tuple[Optional[object], Optional[object]]:
    """(min, max) of the column's valid values as host python objects in the
    column's logical domain (dates as datetime.date, strings as str).
    Returns (None, None) when every row is null."""
    import datetime

    from . import pallas_kernels

    if col.data.shape[0] == 0:
        return None, None
    # Shape classes: padded to the length class, pad rows masked like
    # nulls — per-file builds at one class share one compiled reduction.
    data, n = shapes.pad_class(col.data)
    validity = col.validity
    if validity is not None:
        validity = shapes.pad_to(validity, int(data.shape[0]), False)
    elif shapes.is_padded(data, n):
        validity = shapes.valid_mask(int(data.shape[0]), n)
    # 32-bit lanes go through the fused one-pass Pallas reduction on TPU.
    use_pallas = (pallas_kernels.enabled() and data.shape[0] > 0
                  and data.dtype in (jnp.int32, jnp.float32))
    if validity is not None:
        if col.validity is not None:
            n_valid = int(jnp.sum(validity))
            if n_valid == 0:
                return None, None
        if use_pallas:
            mn, mx = pallas_kernels.masked_minmax(data, validity)
        else:
            lo_sent = _max_sentinel(data.dtype)
            hi_sent = _min_sentinel(data.dtype)
            mn = jnp.min(jnp.where(validity, data, lo_sent))
            mx = jnp.max(jnp.where(validity, data, hi_sent))
    else:
        if use_pallas:
            mn, mx = pallas_kernels.masked_minmax(data)
        else:
            mn, mx = jnp.min(data), jnp.max(data)
    mn, mx = jax.device_get((mn, mx))
    if col.dtype == STRING:
        return str(col.dictionary[int(mn)]), str(col.dictionary[int(mx)])
    if col.dtype == DATE:
        epoch = datetime.date(1970, 1, 1)
        return (epoch + datetime.timedelta(days=int(mn)),
                epoch + datetime.timedelta(days=int(mx)))
    return mn.item(), mx.item()


def _max_sentinel(dtype):
    return jnp.array(jnp.finfo(dtype).max if jnp.issubdtype(dtype, jnp.floating)
                     else jnp.iinfo(dtype).max, dtype)


def _min_sentinel(dtype):
    return jnp.array(jnp.finfo(dtype).min if jnp.issubdtype(dtype, jnp.floating)
                     else jnp.iinfo(dtype).min, dtype)
