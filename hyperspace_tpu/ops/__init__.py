from . import kernels  # noqa: F401
