"""Columnar schema model.

The reference stores Spark ``StructType`` JSON strings in index metadata
(index/IndexLogEntry.scala:355 ``schemaString``). This is our equivalent: a
flat list of typed, nullable fields with a stable JSON encoding, convertible
to/from pyarrow schemas at the IO boundary.

Logical types are deliberately few and TPU-friendly: every type has a fixed-
width device representation (strings become order-preserving dictionary codes
at load time, see execution/columnar.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence

import pyarrow as pa

# Logical type names.
INT32 = "int32"
INT64 = "int64"
FLOAT32 = "float32"
FLOAT64 = "float64"
BOOL = "bool"
STRING = "string"
DATE = "date"  # days since epoch, int32 on device.

_ALL_TYPES = (INT32, INT64, FLOAT32, FLOAT64, BOOL, STRING, DATE)

_ARROW_TO_LOGICAL = {
    pa.int8(): INT32,
    pa.int16(): INT32,
    pa.int32(): INT32,
    pa.int64(): INT64,
    pa.float32(): FLOAT32,
    pa.float64(): FLOAT64,
    pa.bool_(): BOOL,
    pa.string(): STRING,
    pa.large_string(): STRING,
    pa.date32(): DATE,
}

_LOGICAL_TO_ARROW = {
    INT32: pa.int32(),
    INT64: pa.int64(),
    FLOAT32: pa.float32(),
    FLOAT64: pa.float64(),
    BOOL: pa.bool_(),
    STRING: pa.string(),
    DATE: pa.date32(),
}


@dataclass(frozen=True)
class Field:
    name: str
    dtype: str
    nullable: bool = True

    def __post_init__(self):
        if self.dtype not in _ALL_TYPES:
            raise ValueError(f"Unsupported logical type: {self.dtype}")

    def to_json_dict(self) -> Dict:
        return {"name": self.name, "type": self.dtype, "nullable": self.nullable}

    @staticmethod
    def from_json_dict(d: Dict) -> "Field":
        return Field(d["name"], d["type"], d.get("nullable", True))


@dataclass(frozen=True)
class Schema:
    fields: tuple

    def __init__(self, fields: Sequence[Field]):
        object.__setattr__(self, "fields", tuple(fields))

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        return any(f.name == name for f in self.fields)

    def select(self, names: Sequence[str]) -> "Schema":
        return Schema([self.field(n) for n in names])

    def append(self, field: Field) -> "Schema":
        return Schema(list(self.fields) + [field])

    def to_json_dict(self) -> List[Dict]:
        return [f.to_json_dict() for f in self.fields]

    @staticmethod
    def from_json_dict(d: List[Dict]) -> "Schema":
        return Schema([Field.from_json_dict(x) for x in d])

    def to_arrow(self) -> pa.Schema:
        return pa.schema([pa.field(f.name, _LOGICAL_TO_ARROW[f.dtype], f.nullable)
                          for f in self.fields])

    @staticmethod
    def from_arrow(arrow_schema: pa.Schema) -> "Schema":
        """Struct fields are flattened recursively into dotted leaf names
        (``a.b.c``) — nested data never reaches the device as structs; each
        leaf is an independent flat column (parity with the reference's
        nested-field flattening, util/ResolverUtils.scala:112-162, minus its
        ``__hs_nested.`` storage prefix, which Spark needed only because
        Catalyst attribute names cannot contain dots)."""
        fields = []

        def add(prefix: str, f) -> None:
            t = f.type
            name = f"{prefix}{f.name}"
            if pa.types.is_struct(t):
                for sub in t:
                    add(f"{name}.", sub)
                return
            if pa.types.is_dictionary(t):
                t = t.value_type
            if pa.types.is_decimal(t):
                logical = FLOAT64
            elif pa.types.is_timestamp(t):
                logical = INT64
            elif t in _ARROW_TO_LOGICAL:
                logical = _ARROW_TO_LOGICAL[t]
            else:
                raise ValueError(f"Unsupported arrow type for field {name}: {t}")
            fields.append(Field(name, logical, f.nullable))

        for f in arrow_schema:
            add("", f)
        return Schema(fields)
