"""Storage abstraction under the op log (SURVEY §7 hard-part 4).

The local filesystem gives the log its crash consistency through
link-into-place atomicity; object stores have no rename, but they DO
have conditional put (S3 ``If-None-Match: *``, GCS
``if-generation-match: 0``, ADLS ETag preconditions) — and
put-if-absent is the ONLY primitive ``write_log``'s optimistic
concurrency actually needs. This module states that contract once,
keeps the local-FS implementation as the default, and ships an
in-memory conditional-put store the protocol tests run against — so the
log manager is proven to need nothing an object store cannot give
(no rename anywhere in the protocol).

``latestStable`` is a convenience CACHE (a copy of the newest stable
entry), not a correctness participant: ``get_latest_stable_log`` falls
back to the backward scan whenever it is stale, torn, or absent, so a
last-writer-wins overwrite (a plain PUT) suffices for it on every
store. The reference leans on HDFS-compatible ``FileContext.rename``
for the same protocol (IndexLogManagerImpl); the TPU-native runtime
targets object stores directly instead.

Deployments back a cloud scheme by registering a factory:

    from hyperspace_tpu.index import log_store
    log_store.register_scheme("s3", lambda path: MyS3LogStore(path))

Paths without a scheme (or ``file://``) use the local filesystem.

SCOPE: the registration covers the OP LOG — the crash-consistency
surface SURVEY §7 defers. Full object-store residency (index DATA
files, IndexCollectionManager's directory existence gates) is not
wired yet: an object-store deployment today embeds IndexLogManager
with an explicit ``store=`` for the log while index data stays on a
mounted/local path. The protocol tests prove the log side needs no
further primitives.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional

from ..exceptions import HyperspaceException
from ..util import file_utils


class LogStore:
    """The op-log storage contract. Only four operations, and only
    ``put_if_absent`` must be atomic — it decides every race."""

    def put_if_absent(self, path: str, data: str) -> bool:
        """Write ``data`` at ``path`` iff nothing exists there; True on
        win. Object-store mapping: conditional PUT (If-None-Match: *)."""
        raise NotImplementedError

    def put_overwrite(self, path: str, data: str) -> None:
        """Last-writer-wins full overwrite (plain PUT). Used only for the
        latestStable cache."""
        raise NotImplementedError

    def read(self, path: str) -> Optional[str]:
        """Contents, or None when absent."""
        raise NotImplementedError

    def list_numeric_ids(self, dirpath: str) -> List[int]:
        """The numeric entry names under ``dirpath`` (LIST prefix)."""
        raise NotImplementedError

    def delete(self, path: str) -> bool:
        """Best-effort delete; True when gone (or already absent)."""
        raise NotImplementedError


class LocalFsLogStore(LogStore):
    """The default store: POSIX link-into-place create, fsync'd."""

    def put_if_absent(self, path: str, data: str) -> bool:
        return file_utils.atomic_create(path, data)

    def put_overwrite(self, path: str, data: str) -> None:
        file_utils.atomic_overwrite(path, data)

    def read(self, path: str) -> Optional[str]:
        if not os.path.exists(path):
            return None
        return file_utils.read_contents(path)

    def list_numeric_ids(self, dirpath: str) -> List[int]:
        if not os.path.isdir(dirpath):
            return []
        return [int(n) for n in os.listdir(dirpath) if n.isdigit()]

    def delete(self, path: str) -> bool:
        try:
            if os.path.exists(path):
                os.unlink(path)
            return True
        except OSError:
            return False


class InMemoryObjectStore(LogStore):
    """A conditional-put object store double: flat key space, LIST by
    prefix, compare-and-create under a lock — the semantics S3/GCS give
    (strong read-after-write consistency, no rename). The log-protocol
    tests run the full CREATING→ACTIVE lifecycle, recovery scans, and
    multi-writer races against this, proving the protocol needs no
    filesystem."""

    def __init__(self):
        self._objects: Dict[str, str] = {}
        self._lock = threading.Lock()

    def put_if_absent(self, path: str, data: str) -> bool:
        with self._lock:  # the conditional PUT
            if path in self._objects:
                return False
            self._objects[path] = data
            return True

    def put_overwrite(self, path: str, data: str) -> None:
        with self._lock:
            self._objects[path] = data

    def read(self, path: str) -> Optional[str]:
        with self._lock:
            return self._objects.get(path)

    def list_numeric_ids(self, dirpath: str) -> List[int]:
        prefix = dirpath.rstrip("/") + "/"
        with self._lock:
            out = []
            for k in self._objects:
                if k.startswith(prefix):
                    tail = k[len(prefix):]
                    if "/" not in tail and tail.isdigit():
                        out.append(int(tail))
            return out

    def delete(self, path: str) -> bool:
        with self._lock:
            self._objects.pop(path, None)
            return True

    # Test hook: simulate a torn tail (crash mid-upload leaves a partial
    # object on stores without atomic multipart completion).
    def corrupt(self, path: str) -> None:
        with self._lock:
            if path in self._objects:
                self._objects[path] = self._objects[path][: 10]


_SCHEME_FACTORIES: Dict[str, Callable[[str], LogStore]] = {}


def register_scheme(scheme: str, factory: Callable[[str], LogStore]) -> None:
    """Back ``scheme://`` index paths with a custom LogStore."""
    _SCHEME_FACTORIES[scheme.lower()] = factory


def strip_file_scheme(path: str) -> str:
    """file:// URIs address the local filesystem: hand os.* the real
    path, never the URI (a literal './file:...' directory otherwise)."""
    if path.lower().startswith("file://"):
        return path[len("file://"):]
    return path


def store_for_path(index_path: str) -> LogStore:
    if "://" in index_path:
        scheme = index_path.split("://", 1)[0].lower()
        if scheme in ("file", ""):
            return LocalFsLogStore()
        factory = _SCHEME_FACTORIES.get(scheme)
        if factory is None:
            raise HyperspaceException(
                f"No LogStore registered for scheme {scheme!r}; register "
                "one with hyperspace_tpu.index.log_store.register_scheme "
                "(the store only needs conditional put — see the module "
                "docstring for the exact contract)")
        return factory(index_path)
    return LocalFsLogStore()


# Built-in scheme registrations (hsmem:// — the in-memory data+log test
# double) live in data_store; importing it here makes them available the
# moment any store resolution happens.
from . import data_store as _data_store  # noqa: E402,F401  (registration side effect)
