"""Logical-plan signature providers: index validity fingerprints.

Parity reference: index/LogicalPlanSignatureProvider.scala:63-96,
FileBasedSignatureProvider.scala:30, PlanSignatureProvider.scala:29,
IndexSignatureProvider.scala:35.

An index is applicable to a plan iff the plan's fingerprint (as computed by
the provider recorded in the index's metadata) matches the fingerprint stored
at index creation. Pluggable by dotted class path.
"""

from __future__ import annotations

import importlib
from typing import Optional

from ..exceptions import HyperspaceException
from ..util import hashing


class LogicalPlanSignatureProvider:
    def name(self) -> str:
        return f"{type(self).__module__}.{type(self).__qualname__}"

    def signature(self, plan) -> Optional[str]:
        """Fingerprint of the plan, or None if this provider can't handle it."""
        raise NotImplementedError

    @staticmethod
    def create(name: Optional[str] = None) -> "LogicalPlanSignatureProvider":
        if name is None:
            return IndexSignatureProvider()
        short = {
            "FileBasedSignatureProvider": FileBasedSignatureProvider,
            "PlanSignatureProvider": PlanSignatureProvider,
            "IndexSignatureProvider": IndexSignatureProvider,
        }
        if name in short:
            return short[name]()
        module_name, _, cls_name = name.rpartition(".")
        if cls_name in short:
            return short[cls_name]()
        try:
            cls = getattr(importlib.import_module(module_name), cls_name)
            return cls()
        except (ImportError, AttributeError, ValueError) as e:
            raise HyperspaceException(f"Unknown signature provider: {name}") from e


class FileBasedSignatureProvider(LogicalPlanSignatureProvider):
    """md5 over each source file's (size, mtime, path), combined across all
    file-based relation leaves of the plan."""

    def signature(self, plan) -> Optional[str]:
        parts = []
        for leaf in plan.collect_leaves():
            relation = getattr(leaf, "relation", None)
            if relation is None:
                return None
            for path, size, mtime in relation.all_file_infos():
                parts.append(f"{size}{mtime}{path}")
        if not parts:
            return None
        return hashing.md5_hex("".join(parts))


class PlanSignatureProvider(LogicalPlanSignatureProvider):
    """md5 over the plan's operator node names (structure fingerprint)."""

    def signature(self, plan) -> Optional[str]:
        return hashing.md5_hex("".join(plan.node_names_preorder()))


class IndexSignatureProvider(LogicalPlanSignatureProvider):
    """File-based + plan signatures combined — the default provider
    (parity: IndexSignatureProvider.scala:35)."""

    def signature(self, plan) -> Optional[str]:
        fb = FileBasedSignatureProvider().signature(plan)
        if fb is None:
            return None
        ps = PlanSignatureProvider().signature(plan)
        return hashing.md5_hex(fb + ps)
