"""Index metadata model: the versioned, JSON-serialized operation-log entry.

Parity reference: index/IndexLogEntry.scala:43-722. The JSON layout mirrors
the reference's (kind-discriminated nodes, Content directory tree, Source
plan with fingerprint) so that concepts map one-to-one:

  LogEntry            — base: state / id / version tag
  Content             — directory tree of index files (sizes, mtimes, fileIds)
  Directory/FileInfo  — tree nodes
  CoveringIndex       — derived-dataset descriptor (indexed/included cols, buckets)
  DataSkippingIndex   — second derived-dataset kind (MinMax/Bloom sketches);
                        anticipated by the reference's `kind` field
                        (IndexLogEntry.scala:349) but only present in later
                        reference versions.
  Signature           — (provider, value)
  LogicalPlanFingerprint — list of signatures over the source plan
  Update              — appended/deleted file sets since content was captured
  Hdfs / Relation / SourcePlan / Source — source-data description
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..exceptions import HyperspaceException
from ..schema import Schema
from ..util import file_utils, json_utils
from .constants import IndexConstants

HYPERSPACE_VERSION = "0.1.0-tpu"
LOG_ENTRY_VERSION = "0.1"


# ---------------------------------------------------------------------------
# Files and directory trees.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FileInfo:
    """A leaf file: name (or full path), size, mtime (ms), tracker id.

    Equality/hash ignore ``id`` (reference: IndexLogEntry.scala:322-335) so
    file-diffing by (name, size, mtime) works across log versions.
    """

    name: str
    size: int
    modifiedTime: int
    id: int = IndexConstants.UNKNOWN_FILE_ID

    def __eq__(self, other):
        return (isinstance(other, FileInfo)
                and self.name == other.name
                and self.size == other.size
                and self.modifiedTime == other.modifiedTime)

    def __hash__(self):
        return hash((self.name, self.size, self.modifiedTime))

    @staticmethod
    def from_path(path: str, file_id: int, as_full_path: bool = True) -> "FileInfo":
        full, size, mtime = file_utils.file_info_triple(path)
        name = full if as_full_path else os.path.basename(full)
        return FileInfo(name, size, mtime, file_id)

    def to_json_dict(self) -> Dict:
        return {"name": self.name, "size": self.size,
                "modifiedTime": self.modifiedTime, "id": self.id}

    @staticmethod
    def from_json_dict(d: Dict) -> "FileInfo":
        return FileInfo(d["name"], d["size"], d["modifiedTime"],
                        d.get("id", IndexConstants.UNKNOWN_FILE_ID))


@dataclass
class Directory:
    """Tree node: directory name, leaf files, subdirectories.

    Parity: IndexLogEntry.scala:85-280 (Directory.fromDirectory/fromLeafFiles,
    merge).
    """

    name: str
    files: List[FileInfo] = dc_field(default_factory=list)
    subDirs: List["Directory"] = dc_field(default_factory=list)

    def merge(self, other: "Directory") -> "Directory":
        if self.name != other.name:
            raise HyperspaceException(
                f"Merging directories with names {self.name} and {other.name} failed.")
        merged_files = list(self.files) + list(other.files)
        mine = {d.name: d for d in self.subDirs}
        theirs = {d.name: d for d in other.subDirs}
        merged_subdirs = []
        for dir_name in sorted(set(mine) | set(theirs)):
            if dir_name in mine and dir_name in theirs:
                merged_subdirs.append(mine[dir_name].merge(theirs[dir_name]))
            else:
                merged_subdirs.append(mine.get(dir_name) or theirs[dir_name])
        return Directory(self.name, merged_files, merged_subdirs)

    def to_json_dict(self) -> Dict:
        return {"name": self.name,
                "files": [f.to_json_dict() for f in self.files],
                "subDirs": [d.to_json_dict() for d in self.subDirs]}

    @staticmethod
    def from_json_dict(d: Dict) -> "Directory":
        return Directory(
            d["name"],
            [FileInfo.from_json_dict(f) for f in d.get("files", [])],
            [Directory.from_json_dict(s) for s in d.get("subDirs", [])])

    @staticmethod
    def from_leaf_files(paths: Sequence[str], file_id_tracker: "FileIdTracker",
                        as_full_name_in_info: bool = False) -> "Directory":
        """Build a rooted tree from a list of absolute leaf-file paths."""
        root = Directory(name="/")
        dir_nodes: Dict[str, Directory] = {"/": root}

        def node_for(dir_path: str) -> Directory:
            dir_path = dir_path.rstrip("/") or "/"
            if dir_path in dir_nodes:
                return dir_nodes[dir_path]
            if "://" in dir_path and "/" not in dir_path.split("://", 1)[1]:
                # Object-store scheme root ("hsmem://bucket"): one opaque
                # child of "/" so the scheme survives the tree round-trip
                # (os.path.dirname would collapse the double slash).
                node = Directory(name=dir_path)
                root.subDirs.append(node)
                dir_nodes[dir_path] = node
                return node
            parent = node_for(os.path.dirname(dir_path))
            node = Directory(name=os.path.basename(dir_path))
            parent.subDirs.append(node)
            dir_nodes[dir_path] = node
            return node

        for p in sorted(paths):
            if "://" not in p:
                p = os.path.abspath(p)  # store paths are already rooted
            # Stat exactly once so the tracker key and the recorded FileInfo
            # can never disagree if the file changes mid-listing.
            full, size, mtime = file_utils.file_info_triple(p)
            fid = file_id_tracker.add_file(full, size, mtime)
            name = full if as_full_name_in_info else os.path.basename(full)
            node_for(os.path.dirname(p)).files.append(FileInfo(name, size, mtime, fid))
        return root


@dataclass
class NoOpFingerprint:
    kind: str = "NoOp"
    properties: Dict[str, str] = dc_field(default_factory=dict)

    def to_json_dict(self) -> Dict:
        return {"kind": self.kind, "properties": dict(self.properties)}

    @staticmethod
    def from_json_dict(d: Dict) -> "NoOpFingerprint":
        return NoOpFingerprint(d.get("kind", "NoOp"), d.get("properties", {}))


@dataclass
class Content:
    """Directory tree + fingerprint; knows how to enumerate its leaf files
    with full paths (parity: IndexLogEntry.scala:43-84)."""

    root: Directory
    fingerprint: NoOpFingerprint = dc_field(default_factory=NoOpFingerprint)

    def _walk(self):
        """Yield (full_path, FileInfo) for every leaf file in the tree."""

        def rec(node: Directory, prefix: str):
            if "://" in node.name:
                base = node.name  # object-store scheme root is absolute
            else:
                base = os.path.join(prefix, node.name) \
                    if node.name != "/" else "/"
            for f in node.files:
                full = f.name if os.path.isabs(f.name) else os.path.join(base, f.name)
                yield full, f
            for sub in node.subDirs:
                yield from rec(sub, base)

        yield from rec(self.root, "")

    @property
    def files(self) -> List[str]:
        return [full for full, _ in self._walk()]

    @property
    def file_infos(self) -> Set[FileInfo]:
        return {FileInfo(full, f.size, f.modifiedTime, f.id) for full, f in self._walk()}

    def merge(self, other: "Content") -> "Content":
        return Content(self.root.merge(other.root), self.fingerprint)

    def to_json_dict(self) -> Dict:
        return {"root": self.root.to_json_dict(),
                "fingerprint": self.fingerprint.to_json_dict()}

    @staticmethod
    def from_json_dict(d: Dict) -> "Content":
        return Content(Directory.from_json_dict(d["root"]),
                       NoOpFingerprint.from_json_dict(d.get("fingerprint", {})))

    @staticmethod
    def from_directory(path: str, file_id_tracker: "FileIdTracker") -> "Content":
        leaf = file_utils.list_leaf_files(path)
        return Content(Directory.from_leaf_files(leaf, file_id_tracker))

    @staticmethod
    def from_leaf_files(paths: Sequence[str],
                        file_id_tracker: "FileIdTracker") -> Optional["Content"]:
        if not paths:
            return None
        return Content(Directory.from_leaf_files(paths, file_id_tracker))


# ---------------------------------------------------------------------------
# Derived datasets.
# ---------------------------------------------------------------------------

@dataclass
class CoveringIndex:
    """Bucketed+sorted columnar copy descriptor (IndexLogEntry.scala:348-361)."""

    indexed_columns: List[str]
    included_columns: List[str]
    schema: Schema
    num_buckets: int
    properties: Dict[str, str] = dc_field(default_factory=dict)

    kind = "CoveringIndex"
    kind_abbr = "CI"

    def to_json_dict(self) -> Dict:
        return {
            "kind": self.kind,
            "properties": {
                "columns": {"indexed": list(self.indexed_columns),
                            "included": list(self.included_columns)},
                "schema": self.schema.to_json_dict(),
                "numBuckets": self.num_buckets,
                "properties": dict(self.properties),
            },
        }

    @staticmethod
    def from_json_dict(d: Dict) -> "CoveringIndex":
        p = d["properties"]
        return CoveringIndex(
            list(p["columns"]["indexed"]), list(p["columns"]["included"]),
            Schema.from_json_dict(p["schema"]), p["numBuckets"],
            dict(p.get("properties", {})))


@dataclass
class Sketch:
    """A single data-skipping sketch over one column."""

    kind: str  # "MinMax" | "BloomFilter"
    column: str
    properties: Dict[str, str] = dc_field(default_factory=dict)

    def to_json_dict(self) -> Dict:
        return {"kind": self.kind, "column": self.column,
                "properties": dict(self.properties)}

    @staticmethod
    def from_json_dict(d: Dict) -> "Sketch":
        return Sketch(d["kind"], d["column"], dict(d.get("properties", {})))


@dataclass
class DataSkippingIndex:
    """Per-source-file sketches for scan pruning (a capability of later
    reference versions; see SURVEY.md version note)."""

    sketches: List[Sketch]
    schema: Schema  # schema of the sketch table.
    properties: Dict[str, str] = dc_field(default_factory=dict)

    kind = "DataSkippingIndex"
    kind_abbr = "DS"

    # A data-skipping index has no bucketing.
    num_buckets = 1

    @property
    def indexed_columns(self) -> List[str]:
        return [s.column for s in self.sketches]

    @property
    def included_columns(self) -> List[str]:
        return []

    def to_json_dict(self) -> Dict:
        return {
            "kind": self.kind,
            "properties": {
                "sketches": [s.to_json_dict() for s in self.sketches],
                "schema": self.schema.to_json_dict(),
                "properties": dict(self.properties),
            },
        }

    @staticmethod
    def from_json_dict(d: Dict) -> "DataSkippingIndex":
        p = d["properties"]
        return DataSkippingIndex(
            [Sketch.from_json_dict(s) for s in p["sketches"]],
            Schema.from_json_dict(p["schema"]),
            dict(p.get("properties", {})))


@dataclass
class IngestedTable:
    """Streaming-table descriptor: the derived dataset of a per-table
    ingestion op-log entry (streaming/ingest.py). There is no derived
    DATA — the table's own files are the payload; the entry's content
    tree records which ingested batch files each commit published, so
    crash recovery can tell a committed batch from a torn one."""

    schema: Schema
    properties: Dict[str, str] = dc_field(default_factory=dict)

    kind = "IngestedTable"
    kind_abbr = "IT"

    # Lifecycle-action compatibility (CancelAction round-trips entries).
    num_buckets = 1
    indexed_columns: List[str] = dc_field(default_factory=list)
    included_columns: List[str] = dc_field(default_factory=list)

    def to_json_dict(self) -> Dict:
        return {
            "kind": self.kind,
            "properties": {
                "schema": self.schema.to_json_dict(),
                "properties": dict(self.properties),
            },
        }

    @staticmethod
    def from_json_dict(d: Dict) -> "IngestedTable":
        p = d["properties"]
        return IngestedTable(Schema.from_json_dict(p["schema"]),
                             dict(p.get("properties", {})))


def derived_dataset_from_json(d: Dict):
    kind = d.get("kind")
    if kind == "CoveringIndex":
        return CoveringIndex.from_json_dict(d)
    if kind == "DataSkippingIndex":
        return DataSkippingIndex.from_json_dict(d)
    if kind == "IngestedTable":
        return IngestedTable.from_json_dict(d)
    raise HyperspaceException(f"Unknown derived dataset kind: {kind}")


# ---------------------------------------------------------------------------
# Source description.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Signature:
    provider: str
    value: str

    def to_json_dict(self) -> Dict:
        return {"provider": self.provider, "value": self.value}

    @staticmethod
    def from_json_dict(d: Dict) -> "Signature":
        return Signature(d["provider"], d["value"])


@dataclass
class LogicalPlanFingerprint:
    signatures: List[Signature]
    kind: str = "LogicalPlan"

    def to_json_dict(self) -> Dict:
        return {"kind": self.kind,
                "properties": {"signatures": [s.to_json_dict() for s in self.signatures]}}

    @staticmethod
    def from_json_dict(d: Dict) -> "LogicalPlanFingerprint":
        return LogicalPlanFingerprint(
            [Signature.from_json_dict(s) for s in d["properties"]["signatures"]],
            d.get("kind", "LogicalPlan"))


@dataclass
class Update:
    """Appended/deleted source files since content capture (quick refresh)."""

    appendedFiles: Optional[Content] = None
    deletedFiles: Optional[Content] = None

    def to_json_dict(self) -> Dict:
        return {
            "appendedFiles": self.appendedFiles.to_json_dict() if self.appendedFiles else None,
            "deletedFiles": self.deletedFiles.to_json_dict() if self.deletedFiles else None,
        }

    @staticmethod
    def from_json_dict(d: Optional[Dict]) -> Optional["Update"]:
        if not d:
            return None
        return Update(
            Content.from_json_dict(d["appendedFiles"]) if d.get("appendedFiles") else None,
            Content.from_json_dict(d["deletedFiles"]) if d.get("deletedFiles") else None)


@dataclass
class Hdfs:
    content: Content
    update: Optional[Update] = None
    kind: str = "HDFS"

    def to_json_dict(self) -> Dict:
        return {"kind": self.kind,
                "properties": {"content": self.content.to_json_dict(),
                               "update": self.update.to_json_dict() if self.update else None}}

    @staticmethod
    def from_json_dict(d: Dict) -> "Hdfs":
        p = d["properties"]
        return Hdfs(Content.from_json_dict(p["content"]),
                    Update.from_json_dict(p.get("update")), d.get("kind", "HDFS"))


@dataclass
class Relation:
    """Source relation descriptor (IndexLogEntry.scala:410-417)."""

    rootPaths: List[str]
    data: Hdfs
    dataSchema: Schema
    fileFormat: str
    options: Dict[str, str] = dc_field(default_factory=dict)

    def to_json_dict(self) -> Dict:
        return {"rootPaths": list(self.rootPaths), "data": self.data.to_json_dict(),
                "dataSchema": self.dataSchema.to_json_dict(),
                "fileFormat": self.fileFormat, "options": dict(self.options)}

    @staticmethod
    def from_json_dict(d: Dict) -> "Relation":
        return Relation(list(d["rootPaths"]), Hdfs.from_json_dict(d["data"]),
                        Schema.from_json_dict(d["dataSchema"]), d["fileFormat"],
                        dict(d.get("options", {})))


@dataclass
class SourcePlan:
    """Source plan: relations + fingerprint (reference's `SparkPlan` node,
    IndexLogEntry.scala:418-431 — renamed, there is no Spark here)."""

    relations: List[Relation]
    fingerprint: LogicalPlanFingerprint
    rawPlan: Optional[str] = None
    sql: Optional[str] = None
    kind: str = "Plan"

    def to_json_dict(self) -> Dict:
        return {"kind": self.kind,
                "properties": {"relations": [r.to_json_dict() for r in self.relations],
                               "rawPlan": self.rawPlan, "sql": self.sql,
                               "fingerprint": self.fingerprint.to_json_dict()}}

    @staticmethod
    def from_json_dict(d: Dict) -> "SourcePlan":
        p = d["properties"]
        return SourcePlan(
            [Relation.from_json_dict(r) for r in p["relations"]],
            LogicalPlanFingerprint.from_json_dict(p["fingerprint"]),
            p.get("rawPlan"), p.get("sql"), d.get("kind", "Plan"))


@dataclass
class Source:
    plan: SourcePlan

    def to_json_dict(self) -> Dict:
        return {"plan": self.plan.to_json_dict()}

    @staticmethod
    def from_json_dict(d: Dict) -> "Source":
        return Source(SourcePlan.from_json_dict(d["plan"]))


# ---------------------------------------------------------------------------
# Log entries.
# ---------------------------------------------------------------------------

@dataclass
class LogEntry:
    """Base log entry: state + id + timestamp (IndexLogEntry.scala LogEntry)."""

    state: str = ""
    id: int = 0
    timestamp: int = 0
    version: str = LOG_ENTRY_VERSION


@dataclass
class IndexLogEntry(LogEntry):
    """One committed version of an index's metadata."""

    name: str = ""
    derivedDataset: object = None  # CoveringIndex | DataSkippingIndex
    content: Content = None
    source: Source = None
    properties: Dict[str, str] = dc_field(default_factory=dict)

    # ------------------------------------------------------------------
    # Convenience accessors (parity with IndexLogEntry.scala lazy vals).
    # ------------------------------------------------------------------

    @property
    def created(self) -> bool:
        from .constants import States
        return self.state == States.ACTIVE

    @property
    def relations(self) -> List[Relation]:
        assert len(self.source.plan.relations) == 1
        return self.source.plan.relations

    @property
    def relation(self) -> Relation:
        return self.relations[0]

    @property
    def source_file_info_set(self) -> Set[FileInfo]:
        return self.relation.data.content.file_infos

    @property
    def source_files_size_in_bytes(self) -> int:
        return sum(f.size for f in self.source_file_info_set)

    @property
    def index_files_size_in_bytes(self) -> int:
        return sum(f.size for f in self.content.file_infos)

    @property
    def source_update(self) -> Optional[Update]:
        return self.relation.data.update

    @property
    def appended_files(self) -> Set[FileInfo]:
        u = self.source_update
        if u and u.appendedFiles:
            return u.appendedFiles.file_infos
        return set()

    @property
    def deleted_files(self) -> Set[FileInfo]:
        u = self.source_update
        if u and u.deletedFiles:
            return u.deletedFiles.file_infos
        return set()

    @property
    def signature(self) -> LogicalPlanFingerprint:
        return self.source.plan.fingerprint

    @property
    def num_buckets(self) -> int:
        return self.derivedDataset.num_buckets

    @property
    def indexed_columns(self) -> List[str]:
        return self.derivedDataset.indexed_columns

    @property
    def included_columns(self) -> List[str]:
        return self.derivedDataset.included_columns

    @property
    def schema(self) -> Schema:
        return self.derivedDataset.schema

    def has_lineage_column(self) -> bool:
        return self.derivedDataset.properties.get(
            IndexConstants.LINEAGE_PROPERTY, "false").lower() == "true"

    def has_parquet_as_source_format(self) -> bool:
        return self.derivedDataset.properties.get(
            IndexConstants.HAS_PARQUET_AS_SOURCE_FORMAT_PROPERTY, "false").lower() == "true"

    @property
    def log_version(self) -> int:
        return int(self.properties.get(IndexConstants.INDEX_LOG_VERSION, self.id))

    def with_log_version(self, version: int) -> "IndexLogEntry":
        props = dict(self.properties)
        props[IndexConstants.INDEX_LOG_VERSION] = str(version)
        entry = IndexLogEntry(
            state=self.state, id=self.id, timestamp=self.timestamp, version=self.version,
            name=self.name, derivedDataset=self.derivedDataset, content=self.content,
            source=self.source, properties=props)
        return entry

    # Mutable, non-serialized rule tags (IndexLogEntry.scala tags).
    _tags: Dict = dc_field(default_factory=dict, repr=False, compare=False)

    def set_tag(self, plan_key, tag: str, value) -> None:
        self._tags[(plan_key, tag)] = value

    def get_tag(self, plan_key, tag: str):
        return self._tags.get((plan_key, tag))

    def unset_tag(self, plan_key, tag: str) -> None:
        self._tags.pop((plan_key, tag), None)

    # ------------------------------------------------------------------
    # JSON round trip.
    # ------------------------------------------------------------------

    def to_json_dict(self) -> Dict:
        return {
            "name": self.name,
            "derivedDataset": self.derivedDataset.to_json_dict(),
            "content": self.content.to_json_dict(),
            "source": self.source.to_json_dict(),
            "properties": dict(self.properties),
            "state": self.state,
            "id": self.id,
            "timestamp": self.timestamp,
            "version": self.version,
        }

    def to_json(self) -> str:
        return json_utils.to_json(self.to_json_dict())

    @staticmethod
    def from_json_dict(d: Dict) -> "IndexLogEntry":
        return IndexLogEntry(
            state=d["state"], id=d["id"], timestamp=d.get("timestamp", 0),
            version=d.get("version", LOG_ENTRY_VERSION), name=d["name"],
            derivedDataset=derived_dataset_from_json(d["derivedDataset"]),
            content=Content.from_json_dict(d["content"]),
            source=Source.from_json_dict(d["source"]),
            properties=dict(d.get("properties", {})))

    @staticmethod
    def from_json(text: str) -> "IndexLogEntry":
        return IndexLogEntry.from_json_dict(json_utils.from_json(text))

    @staticmethod
    def create(name: str, derived_dataset, content: Content, source: Source,
               properties: Dict[str, str]) -> "IndexLogEntry":
        props = dict(properties)
        props[IndexConstants.HYPERSPACE_VERSION_PROPERTY] = HYPERSPACE_VERSION
        return IndexLogEntry(name=name, derivedDataset=derived_dataset, content=content,
                             source=source, properties=props)


class FileIdTracker:
    """Generates unique ids per (path, size, mtime) triple
    (parity: IndexLogEntry.scala:653-722)."""

    def __init__(self):
        self._max_id = -1
        self._file_to_id: Dict[Tuple[str, int, int], int] = {}

    @property
    def max_file_id(self) -> int:
        return self._max_id

    @property
    def file_to_id_mapping(self) -> Dict[Tuple[str, int, int], int]:
        return dict(self._file_to_id)

    def get_file_id(self, path: str, size: int, mtime: int) -> Optional[int]:
        return self._file_to_id.get((path, size, mtime))

    def add_file_info(self, files: Set[FileInfo]) -> None:
        for f in files:
            if f.id == IndexConstants.UNKNOWN_FILE_ID:
                raise HyperspaceException(
                    f"Cannot add file info with unknown id. (file: {f.name}).")
            key = (f.name, f.size, f.modifiedTime)
            existing = self._file_to_id.get(key)
            if existing is not None:
                if existing != f.id:
                    raise HyperspaceException(
                        "Adding file info with a conflicting id. "
                        f"(existing id: {existing}, new id: {f.id}, file: {f.name}).")
            else:
                self._file_to_id[key] = f.id
                self._max_id = max(self._max_id, f.id)

    def add_file(self, path: str, size: int, mtime: int) -> int:
        key = (path, size, mtime)
        if key not in self._file_to_id:
            self._max_id += 1
            self._file_to_id[key] = self._max_id
        return self._file_to_id[key]
