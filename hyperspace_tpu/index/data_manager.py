"""Physical index data layout: immutable versioned directories.

Parity reference: index/IndexDataManager.scala:38-74. Layout:

    <indexPath>/v__=<version>/<bucket files>.parquet
"""

from __future__ import annotations

import os
import re
from typing import List, Optional

from ..util import file_utils
from .constants import IndexConstants


class IndexDataManager:
    def __init__(self, index_path: str):
        self._index_path = index_path
        self._prefix = IndexConstants.INDEX_VERSION_DIRECTORY_PREFIX + "="

    @property
    def index_path(self) -> str:
        return self._index_path

    def get_latest_version_id(self) -> Optional[int]:
        versions = self.get_all_version_ids()
        return max(versions) if versions else None

    def get_all_version_ids(self) -> List[int]:
        if not file_utils.is_dir(self._index_path):
            return []
        pattern = re.compile(re.escape(self._prefix) + r"(\d+)$")
        out = []
        for name in file_utils.list_dir(self._index_path):
            m = pattern.match(name)
            if m and file_utils.is_dir(
                    os.path.join(self._index_path, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def get_path(self, version: int) -> str:
        return os.path.join(self._index_path, f"{self._prefix}{version}")

    def delete(self, version: int) -> None:
        file_utils.delete_recursively(self.get_path(version))
