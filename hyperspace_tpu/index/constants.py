"""Framework-wide constants: action states, config keys, on-disk layout names.

Parity reference: /root/reference src/main/scala/com/microsoft/hyperspace/actions/Constants.scala
and index/IndexConstants.scala (keys renamed from ``spark.hyperspace.*`` to
``hyperspace.*`` since there is no Spark session here).
"""

from __future__ import annotations


class States:
    """Index lifecycle states (reference: actions/Constants.scala:19-31)."""

    ACTIVE = "ACTIVE"
    CREATING = "CREATING"
    DELETING = "DELETING"
    DELETED = "DELETED"
    REFRESHING = "REFRESHING"
    VACUUMING = "VACUUMING"
    RESTORING = "RESTORING"
    OPTIMIZING = "OPTIMIZING"
    DOESNOTEXIST = "DOESNOTEXIST"
    CANCELLING = "CANCELLING"


STABLE_STATES = frozenset({States.ACTIVE, States.DELETED, States.DOESNOTEXIST})


class IndexConstants:
    """Config keys + defaults (reference: index/IndexConstants.scala:21-116)."""

    INDEXES_DIR = "indexes"

    # Root ("system") path under which all indexes live.
    INDEX_SYSTEM_PATH = "hyperspace.system.path"

    INDEX_NUM_BUCKETS = "hyperspace.index.numBuckets"
    INDEX_NUM_BUCKETS_DEFAULT = 200

    INDEX_HYBRID_SCAN_ENABLED = "hyperspace.index.hybridscan.enabled"
    INDEX_HYBRID_SCAN_ENABLED_DEFAULT = "false"

    INDEX_HYBRID_SCAN_DELETED_RATIO_THRESHOLD = "hyperspace.index.hybridscan.maxDeletedRatio"
    INDEX_HYBRID_SCAN_DELETED_RATIO_THRESHOLD_DEFAULT = "0.2"

    INDEX_HYBRID_SCAN_APPENDED_RATIO_THRESHOLD = "hyperspace.index.hybridscan.maxAppendedRatio"
    INDEX_HYBRID_SCAN_APPENDED_RATIO_THRESHOLD_DEFAULT = "0.3"

    INDEX_FILTER_RULE_USE_BUCKET_SPEC = "hyperspace.index.filterRule.useBucketSpec"
    INDEX_FILTER_RULE_USE_BUCKET_SPEC_DEFAULT = "false"

    # whyNot reason collection (parity: the FILTER_REASONS tag machinery,
    # rules/IndexFilter.scala:37-52; collection is off by default because
    # building reason strings costs time on the optimize path).
    INDEX_FILTER_REASON_ENABLED = "hyperspace.index.filterReason.enabled"
    INDEX_FILTER_REASON_ENABLED_DEFAULT = "false"

    # Score-based index selection (parity: ApplyHyperspace.scala:69-101 —
    # the reference ships the optimizer as a NoOpRule placeholder; ours is
    # complete and on by default, with the legacy rule order as fallback).
    SCORE_BASED_OPTIMIZER_ENABLED = "hyperspace.optimizer.scoreBased.enabled"
    SCORE_BASED_OPTIMIZER_ENABLED_DEFAULT = "true"

    INDEX_CACHE_EXPIRY_DURATION_SECONDS = "hyperspace.index.cache.expiryDurationInSeconds"
    INDEX_CACHE_EXPIRY_DURATION_SECONDS_DEFAULT = "300"

    # Operation log layout.
    HYPERSPACE_LOG = "_hyperspace_log"
    LATEST_STABLE_LOG_NAME = "latestStable"
    INDEX_VERSION_DIRECTORY_PREFIX = "v__"

    # Explain display modes.
    DISPLAY_MODE = "hyperspace.explain.displayMode"
    HIGHLIGHT_BEGIN_TAG = "hyperspace.explain.displayMode.highlight.beginTag"
    HIGHLIGHT_END_TAG = "hyperspace.explain.displayMode.highlight.endTag"

    class DisplayMode:
        CONSOLE = "console"
        PLAIN_TEXT = "plaintext"
        HTML = "html"

    DATA_FILE_NAME_ID = "_data_file_id"
    INDEX_LINEAGE_ENABLED = "hyperspace.index.lineage.enabled"
    INDEX_LINEAGE_ENABLED_DEFAULT = "false"

    REFRESH_MODE_INCREMENTAL = "incremental"
    REFRESH_MODE_FULL = "full"
    REFRESH_MODE_QUICK = "quick"
    REFRESH_MODES = (REFRESH_MODE_INCREMENTAL, REFRESH_MODE_FULL, REFRESH_MODE_QUICK)

    OPTIMIZE_FILE_SIZE_THRESHOLD = "hyperspace.index.optimize.fileSizeThreshold"
    OPTIMIZE_FILE_SIZE_THRESHOLD_DEFAULT = 256 * 1024 * 1024
    OPTIMIZE_MODE_QUICK = "quick"
    OPTIMIZE_MODE_FULL = "full"
    OPTIMIZE_MODES = (OPTIMIZE_MODE_QUICK, OPTIMIZE_MODE_FULL)

    UNKNOWN_FILE_ID = -1

    # JSON property names used in index metadata.
    LINEAGE_PROPERTY = "lineage"
    HAS_PARQUET_AS_SOURCE_FORMAT_PROPERTY = "hasParquetAsSourceFormat"
    HYPERSPACE_VERSION_PROPERTY = "hyperspaceVersion"
    INDEX_LOG_VERSION = "indexLogVersion"

    GLOBBING_PATTERN_KEY = "hyperspace.source.globbingPattern"

    # Column-name resolution sensitivity (parity: Spark's
    # spark.sql.caseSensitive, which the reference's ResolverUtils reads;
    # default false like Spark).
    CASE_SENSITIVE = "hyperspace.caseSensitive"
    CASE_SENSITIVE_DEFAULT = "false"

    # Pluggable class names (comma separated), mirrors
    # spark.hyperspace.index.sources.fileBasedBuilders and
    # spark.hyperspace.index.signatureProviders.
    FILE_BASED_SOURCE_BUILDERS = "hyperspace.index.sources.fileBasedBuilders"
    EVENT_LOGGER_CLASS = "hyperspace.eventLoggerClass"

    # Parquet row-group size for index files: smaller groups → finer
    # row-group pruning on the sorted indexed columns (the reference leans on
    # Spark's parquet writer defaults; we make it a first-class knob because
    # pruning granularity is the filter-path win).
    INDEX_ROW_GROUP_SIZE = "hyperspace.index.rowGroupSize"
    INDEX_ROW_GROUP_SIZE_DEFAULT = 65536

    # TPU-native execution knobs (no reference analogue: the reference delegates
    # execution to Spark; these control the XLA/Pallas execution path).
    TPU_EXECUTION_ENABLED = "hyperspace.tpu.execution.enabled"
    TPU_EXECUTION_ENABLED_DEFAULT = "true"
    TPU_BUILD_ROWS_PER_SHARD = "hyperspace.tpu.build.rowsPerShard"
    TPU_BUILD_ROWS_PER_SHARD_DEFAULT = str(8 * 1024 * 1024)
    # Device-footprint budget: datasets whose row count exceeds this stream
    # through the build/scan in chunks (host spill per bucket during builds,
    # per-chunk filter evaluation during scans) instead of materializing in
    # HBM at once — SURVEY §7 hard-part #1 (data larger than HBM).
    TPU_MAX_CHUNK_ROWS = "hyperspace.tpu.maxChunkRows"
    TPU_MAX_CHUNK_ROWS_DEFAULT = str(8 * 1024 * 1024)
    TPU_MESH_SHAPE = "hyperspace.tpu.mesh"
    # XLA profiler integration (SURVEY §5 tracing): when set, every plan
    # execution runs under jax.profiler.trace writing TensorBoard-loadable
    # traces (one subdirectory per execution) into this directory.
    TPU_TRACE_DIR = "hyperspace.tpu.trace.dir"
    # When >1 device is visible, index builds run over the whole mesh
    # (all-to-all bucket exchange, parallel/distributed_build.py) — the
    # analogue of the reference's always-distributed Spark build
    # (actions/CreateActionBase.scala:118-121). "true" | "false".
    TPU_DISTRIBUTED_ENABLED = "hyperspace.tpu.distributed.enabled"
    TPU_DISTRIBUTED_ENABLED_DEFAULT = "true"
    # One-device dispatch of the fused SPMD query program: "auto" takes it
    # on accelerators (every host sync is a device round trip there —
    # measured as the round-3 on-chip filter bottleneck) and skips it on
    # CPU (the interpreted executor shares the silicon, so fusing buys
    # nothing and costs compiles). "on"/"off" force.
    TPU_DISTRIBUTED_SINGLE_DEVICE = "hyperspace.tpu.distributed.singleDevice"
    TPU_DISTRIBUTED_SINGLE_DEVICE_DEFAULT = "auto"
    # Mesh construction for the partitioned-jit SPMD tier
    # (parallel/sharding.py). maxDevices caps how many local devices the
    # dispatch mesh spans (0 = all visible devices); fileAlignedScan
    # shards multi-file parquet leaves on file boundaries so each
    # device's rows come from its own files (locality for per-shard host
    # reads; byte-identical either way).
    TPU_DISTRIBUTED_MESH_MAX_DEVICES = \
        "hyperspace.tpu.distributed.mesh.maxDevices"
    TPU_DISTRIBUTED_MESH_MAX_DEVICES_DEFAULT = "0"
    # Cost gate: streams whose leaf holds fewer rows (parquet metadata)
    # than this stay single-device — sharding a few hundred rows over a
    # mesh pays compile + collective overhead for zero win. 0 disables
    # the gate (the SPMD test tier pins 0 to exercise small meshes).
    TPU_DISTRIBUTED_MIN_STREAM_ROWS = \
        "hyperspace.tpu.distributed.minStreamRows"
    TPU_DISTRIBUTED_MIN_STREAM_ROWS_DEFAULT = "4096"
    TPU_DISTRIBUTED_MESH_FILE_ALIGNED_SCAN = \
        "hyperspace.tpu.distributed.mesh.fileAlignedScan"
    TPU_DISTRIBUTED_MESH_FILE_ALIGNED_SCAN_DEFAULT = "true"

    # Shape-class execution (execution/shapes.py): arrays whose length is
    # data-dependent (filter survivors, join match totals, group counts) are
    # padded to a geometric length class with an explicit valid count, so the
    # per-length XLA recompilation storm collapses onto a handful of compiled
    # programs. maxWasteRatio/exactFallbackRows bound the HBM cost: an array
    # of at least exactFallbackRows rows whose padding would waste more than
    # maxWasteRatio of its size runs at its exact shape instead (huge arrays
    # amortize their own compile; the waste would be real memory).
    TPU_SHAPE_BUCKETING_ENABLED = "hyperspace.tpu.execution.shapeBucketing.enabled"
    TPU_SHAPE_BUCKETING_ENABLED_DEFAULT = "true"
    TPU_SHAPE_BUCKETING_GROWTH_FACTOR = \
        "hyperspace.tpu.execution.shapeBucketing.growthFactor"
    TPU_SHAPE_BUCKETING_GROWTH_FACTOR_DEFAULT = "2.0"
    TPU_SHAPE_BUCKETING_MIN_PAD = \
        "hyperspace.tpu.execution.shapeBucketing.minPadElements"
    TPU_SHAPE_BUCKETING_MIN_PAD_DEFAULT = "1024"
    TPU_SHAPE_BUCKETING_MAX_WASTE_RATIO = \
        "hyperspace.tpu.execution.shapeBucketing.maxWasteRatio"
    TPU_SHAPE_BUCKETING_MAX_WASTE_RATIO_DEFAULT = "0.25"
    TPU_SHAPE_BUCKETING_EXACT_FALLBACK_ROWS = \
        "hyperspace.tpu.execution.shapeBucketing.exactFallbackRows"
    TPU_SHAPE_BUCKETING_EXACT_FALLBACK_ROWS_DEFAULT = str(4 * 1024 * 1024)

    # Whole-plan fusion (execution/fusion.py): fuse maximal
    # filter/project/join-probe/aggregate regions into ONE banked XLA
    # program per (region fingerprint, shape-class vector). minStages is
    # the smallest region worth a program (below it the staged per-stage
    # fused kernels are already optimal); clamped to >= 2.
    TPU_FUSION_ENABLED = "hyperspace.tpu.execution.fusion.enabled"
    TPU_FUSION_ENABLED_DEFAULT = "true"
    TPU_FUSION_MIN_STAGES = "hyperspace.tpu.execution.fusion.minStages"
    TPU_FUSION_MIN_STAGES_DEFAULT = "2"

    # Parallel I/O (parallel/io.py): the process-wide bounded reader pool
    # and the producer/consumer prefetch pipelines behind every multi-file
    # read, chunk stream, sketch build, and spill merge. Ordered gather
    # makes results byte-identical at any thread count; maxInflightBytes
    # bounds the estimated bytes held by in-flight reads. threads=0 means
    # auto (min(16, cpu count)); threads=1 restores sequential reads.
    TPU_IO_ENABLED = "hyperspace.tpu.io.enabled"
    TPU_IO_ENABLED_DEFAULT = "true"
    TPU_IO_THREADS = "hyperspace.tpu.io.threads"
    TPU_IO_THREADS_DEFAULT = "0"
    TPU_IO_PREFETCH_DEPTH = "hyperspace.tpu.io.prefetchDepth"
    TPU_IO_PREFETCH_DEPTH_DEFAULT = "2"
    TPU_IO_MAX_INFLIGHT_BYTES = "hyperspace.tpu.io.maxInflightBytes"
    TPU_IO_MAX_INFLIGHT_BYTES_DEFAULT = str(256 * 1024 * 1024)

    # Tiered columnar buffer pool (execution/buffer_pool.py): the
    # process-wide device→host cache of decoded, shape-class-padded scan
    # buffers shared across queries and sessions. deviceBytes/hostBytes
    # budget the two tiers; streamAdmitBytes caps how large a chunked
    # scan (iter_dataset_chunks) may be before the pool declines to
    # materialize its chunk sequence. All keys are EXCLUDED from the
    # result-cache config hash (serving/fingerprint.py) — the pool is a
    # residency choice, not a semantic one.
    TPU_BUFFER_POOL_ENABLED = "hyperspace.tpu.execution.bufferPool.enabled"
    TPU_BUFFER_POOL_ENABLED_DEFAULT = "true"
    TPU_BUFFER_POOL_DEVICE_BYTES = \
        "hyperspace.tpu.execution.bufferPool.deviceBytes"
    TPU_BUFFER_POOL_DEVICE_BYTES_DEFAULT = str(4 * 1024 * 1024 * 1024)
    TPU_BUFFER_POOL_HOST_BYTES = \
        "hyperspace.tpu.execution.bufferPool.hostBytes"
    TPU_BUFFER_POOL_HOST_BYTES_DEFAULT = str(4 * 1024 * 1024 * 1024)
    TPU_BUFFER_POOL_STREAM_ADMIT_BYTES = \
        "hyperspace.tpu.execution.bufferPool.streamAdmitBytes"
    TPU_BUFFER_POOL_STREAM_ADMIT_BYTES_DEFAULT = str(256 * 1024 * 1024)
