"""Versioned operation log with optimistic concurrency.

Parity reference: index/IndexLogManager.scala:33-185. Layout under an index's
root path:

    <indexPath>/_hyperspace_log/<id>        — JSON log entry, immutable
    <indexPath>/_hyperspace_log/latestStable — copy of the latest stable entry

``write_log`` refuses to overwrite an existing id (conditional
put-if-absent), which is how concurrent actions detect conflicts. The
storage behind the protocol is pluggable (log_store.LogStore): local FS
by default, conditional-put object stores by scheme registration — the
protocol uses no rename, so S3/GCS-class stores satisfy it with one
conditional PUT (SURVEY §7 hard-part 4; tests/test_log_store.py runs
the lifecycle against the object-store double).
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

from ..robustness import fault_names as _fn
from ..robustness import faults as _faults
from ..robustness import retry as _retry
from ..util import json_utils
from .constants import IndexConstants, STABLE_STATES, States
from .log_entry import IndexLogEntry
from .log_store import (LocalFsLogStore, LogStore, store_for_path,
                        strip_file_scheme)


class LogLookupCache:
    """Process-wide memo of the hot per-query op-log lookups, keyed on
    the log DIRECTORY's identity token (mtime_ns/ctime_ns).

    Every query recomputes the result-cache key, which re-lists each
    index's ``_hyperspace_log`` and re-reads its latest entry
    (``latest_entry_fingerprint``) — an O(n-entries) directory scan per
    index per query. Under a long-lived append workload the log grows
    with every commit, putting that scan squarely on the serving hot
    path. Any protocol mutation creates or deletes a file in the log
    dir (entry put-if-absent, latestStable tmp+replace), so the dir
    mtime is a sound change token for cross-process writers; same-
    process writers additionally invalidate explicitly (belt and
    braces against coarse filesystem timestamps). Parsed entries are
    cached as their JSON text and re-parsed per hit — callers mutate
    returned entries (e.g. quick refresh sets ``relation.data.update``)
    so handing out a shared object would tear.

    Only :class:`LocalFsLogStore` logs are cacheable (object stores
    have no directory mtime); everything else bypasses the cache.
    """

    _MAX_DIRS = 256  # bound: one slot per live index/table log
    # Racy-token guard (the git index's racy-mtime rule): a dir whose
    # mtime is within this window of NOW may still receive same-stamp
    # writes on coarse-granularity filesystems, so its token is not yet
    # a sound change detector — serve the computed value, don't pin it.
    # Costs nothing on the satellite's target shape (queries vastly
    # outnumber commits; a log quiet for 2 s caches on the next probe).
    _RACY_WINDOW_NS = 2_000_000_000

    def __init__(self):
        self._lock = threading.Lock()
        # log_path -> (token, {kind: value})
        self._dirs = {}
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    @staticmethod
    def _token(log_path: str):
        try:
            st = os.stat(log_path)
        except OSError:
            return ("missing",)
        return (st.st_mtime_ns, st.st_ctime_ns)

    @classmethod
    def _racy(cls, token) -> bool:
        if token == ("missing",):
            return False
        return time.time_ns() - token[0] < cls._RACY_WINDOW_NS

    def get(self, log_path: str, kind: str, compute):
        """Cached value for ``kind`` under ``log_path``; ``compute()``
        runs on a miss and its result is stored under the token observed
        BEFORE the compute (a token that moved during the compute skips
        the store, so a racing write can never pin a stale value)."""
        token = self._token(log_path)
        with self._lock:
            cached = self._dirs.get(log_path)
            if cached is not None and cached[0] == token \
                    and kind in cached[1]:
                self.hits += 1
                return cached[1][kind]
            self.misses += 1
        value = compute()
        if self._racy(token):
            return value  # token too fresh to trust: serve, don't pin
        with self._lock:
            if self._token(log_path) != token:
                return value  # a write landed mid-compute: serve, don't pin
            cached = self._dirs.get(log_path)
            if cached is None or cached[0] != token:
                if len(self._dirs) >= self._MAX_DIRS:
                    self._dirs.pop(next(iter(self._dirs)))
                cached = (token, {})
                self._dirs[log_path] = cached
            cached[1][kind] = value
        return value

    def invalidate(self, log_path: str) -> None:
        with self._lock:
            if self._dirs.pop(log_path, None) is not None:
                self.invalidations += 1

    def clear(self) -> None:
        with self._lock:
            self._dirs.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "invalidations": self.invalidations,
                    "dirs": len(self._dirs)}


_LOOKUP_CACHE = LogLookupCache()


def get_lookup_cache() -> LogLookupCache:
    """The process-wide op-log lookup cache (observability + tests)."""
    return _LOOKUP_CACHE


class IndexLogManager:
    def __init__(self, index_path: str, store: Optional[LogStore] = None):
        self._store = store or store_for_path(index_path)
        if isinstance(self._store, LocalFsLogStore):
            # Local store: a file:// URI must become a real path before
            # os.path.join builds entry paths under it.
            index_path = strip_file_scheme(index_path)
        self._index_path = index_path
        self._log_path = os.path.join(index_path, IndexConstants.HYPERSPACE_LOG)
        self._latest_stable_path = os.path.join(
            self._log_path, IndexConstants.LATEST_STABLE_LOG_NAME)
        # Only local-FS logs carry the directory-mtime change token the
        # lookup cache validates against.
        self._cacheable = isinstance(self._store, LocalFsLogStore)

    @property
    def index_path(self) -> str:
        return self._index_path

    def _path_from_id(self, log_id: int) -> str:
        return os.path.join(self._log_path, str(log_id))

    def _get_log_at(self, path: str) -> Optional[IndexLogEntry]:
        data = self._store.read(path)
        if data is None:
            return None
        return IndexLogEntry.from_json(data)

    def get_log(self, log_id: int) -> Optional[IndexLogEntry]:
        return self._get_log_at(self._path_from_id(log_id))

    def get_latest_id(self) -> Optional[int]:
        if self._cacheable:
            return _LOOKUP_CACHE.get(self._log_path, "latest_id",
                                     self._compute_latest_id)
        return self._compute_latest_id()

    def _compute_latest_id(self) -> Optional[int]:
        ids = self._store.list_numeric_ids(self._log_path)
        return max(ids) if ids else None

    def get_all_ids(self) -> List[int]:
        """Every existing entry id, newest first. Scans iterate THIS —
        never a dense range(latest, -1, -1): compaction leaves the id
        space sparse (one checkpoint entry, ids keep growing), so a
        per-id probe loop would cost O(lifetime commits), not O(live
        entries)."""
        return sorted(self._store.list_numeric_ids(self._log_path),
                      reverse=True)

    def get_latest_log(self) -> Optional[IndexLogEntry]:
        latest = self.get_latest_id()
        return self.get_log(latest) if latest is not None else None

    def _get_log_lenient(self, log_id: int) -> Optional[IndexLogEntry]:
        """get_log that treats an unparseable entry (torn write from a
        crash mid-rename window) as absent — only the recovery scan may be
        this forgiving; normal reads should surface corruption."""
        try:
            return self.get_log(log_id)
        except (ValueError, KeyError, TypeError):
            return None

    def get_latest_stable_log(self) -> Optional[IndexLogEntry]:
        """Latest entry in a STABLE state; falls back to a backward scan past a
        broken tail — including an unparseable (torn) tail entry
        (reference: IndexLogManager.scala:93-117). The resolved entry is
        memoized as JSON text per (log path, dir mtime) and re-parsed
        per call — callers mutate returned entries, so a shared object
        would tear across threads."""
        if self._cacheable:
            text = _LOOKUP_CACHE.get(
                self._log_path, "stable_json",
                lambda: (lambda e: e.to_json() if e is not None else None)(
                    self._compute_latest_stable_log()))
            return IndexLogEntry.from_json(text) if text is not None \
                else None
        return self._compute_latest_stable_log()

    def _compute_latest_stable_log(self) -> Optional[IndexLogEntry]:
        try:
            log = self._get_log_at(self._latest_stable_path)
        except (ValueError, KeyError, TypeError):
            log = None
        if log is not None and log.state not in STABLE_STATES:
            # A stale/invalid latestStable (e.g. crash between write_log and
            # create_latest_stable_log); fall back to the backward scan.
            log = None
        if log is None:
            for log_id in self.get_all_ids():
                entry = self._get_log_lenient(log_id)
                if entry is not None and entry.state in STABLE_STATES:
                    return entry
                if entry is not None and entry.state in (
                        States.CREATING, States.VACUUMING):
                    # Logs before a CREATING/VACUUMING entry are unrelated.
                    return None
            return None
        return log

    def get_index_versions(self, states: List[str]) -> List[int]:
        """Index log versions whose state is in ``states``, newest first,
        stopping at the most recent CREATING/VACUUMING boundary."""
        ids = self.get_all_ids()
        if not ids:
            return []
        latest = ids[0]
        versions: List[int] = []
        for log_id in ids:
            entry = self.get_log(log_id)
            if entry is None:
                continue
            if entry.state in states:
                versions.append(entry.log_version)
            if entry.state in (States.CREATING, States.VACUUMING) and log_id != latest:
                break
        return versions

    def latest_entry_fingerprint(self) -> Optional[tuple]:
        """(latest id, md5 of the latest entry's raw bytes), or None when
        the log is empty. Cheap change detector for the serving result
        cache: a full refresh restarts the log at the SAME ids (fresh
        create cycle), so the id alone cannot pin the index state — the
        entry bytes can, without parsing JSON. Memoized per (log path,
        dir mtime): this runs once per index per QUERY (result-cache key
        derivation), and under an append workload the backing directory
        scan grows with every commit."""
        if self._cacheable:
            return _LOOKUP_CACHE.get(self._log_path, "fingerprint",
                                     self._compute_fingerprint)
        return self._compute_fingerprint()

    def _compute_fingerprint(self) -> Optional[tuple]:
        latest = self._compute_latest_id()
        if latest is None:
            return None
        data = self._store.read(self._path_from_id(latest))
        from ..util import hashing
        return (latest, hashing.md5_hex(data) if data is not None else "")

    def create_latest_stable_log(self, log_id: int) -> bool:
        entry = self.get_log(log_id)
        if entry is None or entry.state not in STABLE_STATES:
            return False
        data = json_utils.to_json(entry.to_json_dict())

        def _put() -> None:
            # Crash window the recovery scan must survive: a kill here
            # leaves the final entry committed but latestStable stale —
            # get_latest_stable_log's backward scan covers it. Transient
            # store errors (OSError on a flaky mount / object store)
            # retry with backoff; the cache is last-writer-wins, so a
            # re-put is always safe.
            _faults.fault_point(_fn.LOG_STABLE)
            self._store.put_overwrite(self._latest_stable_path, data)

        _retry.call(_put, where="log.stable")
        _LOOKUP_CACHE.invalidate(self._log_path)
        return True

    def delete_latest_stable_log(self) -> bool:
        out = self._store.delete(self._latest_stable_path)
        _LOOKUP_CACHE.invalidate(self._log_path)
        return out

    def write_log(self, log_id: int, entry: IndexLogEntry) -> bool:
        """Write entry at ``log_id`` iff that id doesn't exist yet.
        Transient store errors retry (robustness/retry.py): put-if-absent
        decides every race, so re-putting after an OSError keeps exactly
        the protocol's semantics — a retry that loses the race reports
        False like any other loser. One subtlety makes the retry
        outcome-idempotent: a failed attempt may have COMMITTED the
        entry before erroring (e.g. link-into-place succeeded, the temp
        cleanup raised), so a post-transient "loss" whose stored bytes
        are OUR bytes is a win, not a conflict. The fault point inside
        the retried body is where the crash harness kill -9s
        mid-protocol."""
        entry.id = log_id
        path = self._path_from_id(log_id)
        data = json_utils.to_json(entry.to_json_dict())
        state = {"transient": False}

        def _put() -> bool:
            _faults.fault_point(_fn.LOG_WRITE)
            try:
                return self._store.put_if_absent(path, data)
            except _retry.TRANSIENT_TYPES:
                state["transient"] = True
                raise

        won = _retry.call(_put, where="log.write")
        if not won and state["transient"]:
            won = self._store.read(path) == data  # lost to OURSELVES?
        # Invalidate even on loss: the other writer's entry is just as
        # new to this process's memo as our own would have been.
        _LOOKUP_CACHE.invalidate(self._log_path)
        return won

    def delete_log(self, log_id: int) -> bool:
        """Physically remove one entry file — ONLY compaction
        (streaming/compaction.py) may do this, after the checkpoint
        entry superseding it is durably committed."""
        out = self._store.delete(self._path_from_id(log_id))
        _LOOKUP_CACHE.invalidate(self._log_path)
        return out
