"""Versioned operation log with optimistic concurrency.

Parity reference: index/IndexLogManager.scala:33-185. Layout under an index's
root path:

    <indexPath>/_hyperspace_log/<id>        — JSON log entry, immutable
    <indexPath>/_hyperspace_log/latestStable — copy of the latest stable entry

``write_log`` refuses to overwrite an existing id (temp file + atomic
create-if-absent), which is how concurrent actions detect conflicts.
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..util import file_utils, json_utils
from .constants import IndexConstants, STABLE_STATES, States
from .log_entry import IndexLogEntry


class IndexLogManager:
    def __init__(self, index_path: str):
        self._index_path = index_path
        self._log_path = os.path.join(index_path, IndexConstants.HYPERSPACE_LOG)
        self._latest_stable_path = os.path.join(
            self._log_path, IndexConstants.LATEST_STABLE_LOG_NAME)

    @property
    def index_path(self) -> str:
        return self._index_path

    def _path_from_id(self, log_id: int) -> str:
        return os.path.join(self._log_path, str(log_id))

    def _get_log_at(self, path: str) -> Optional[IndexLogEntry]:
        if not os.path.exists(path):
            return None
        return IndexLogEntry.from_json(file_utils.read_contents(path))

    def get_log(self, log_id: int) -> Optional[IndexLogEntry]:
        return self._get_log_at(self._path_from_id(log_id))

    def get_latest_id(self) -> Optional[int]:
        if not os.path.isdir(self._log_path):
            return None
        ids = [int(name) for name in os.listdir(self._log_path) if name.isdigit()]
        return max(ids) if ids else None

    def get_latest_log(self) -> Optional[IndexLogEntry]:
        latest = self.get_latest_id()
        return self.get_log(latest) if latest is not None else None

    def _get_log_lenient(self, log_id: int) -> Optional[IndexLogEntry]:
        """get_log that treats an unparseable entry (torn write from a
        crash mid-rename window) as absent — only the recovery scan may be
        this forgiving; normal reads should surface corruption."""
        try:
            return self.get_log(log_id)
        except (ValueError, KeyError, TypeError):
            return None

    def get_latest_stable_log(self) -> Optional[IndexLogEntry]:
        """Latest entry in a STABLE state; falls back to a backward scan past a
        broken tail — including an unparseable (torn) tail entry
        (reference: IndexLogManager.scala:93-117)."""
        try:
            log = self._get_log_at(self._latest_stable_path)
        except (ValueError, KeyError, TypeError):
            log = None
        if log is not None and log.state not in STABLE_STATES:
            # A stale/invalid latestStable (e.g. crash between write_log and
            # create_latest_stable_log); fall back to the backward scan.
            log = None
        if log is None:
            latest = self.get_latest_id()
            if latest is not None:
                for log_id in range(latest, -1, -1):
                    entry = self._get_log_lenient(log_id)
                    if entry is not None and entry.state in STABLE_STATES:
                        return entry
                    if entry is not None and entry.state in (
                            States.CREATING, States.VACUUMING):
                        # Logs before a CREATING/VACUUMING entry are unrelated.
                        return None
            return None
        return log

    def get_index_versions(self, states: List[str]) -> List[int]:
        """Index log versions whose state is in ``states``, newest first,
        stopping at the most recent CREATING/VACUUMING boundary."""
        latest = self.get_latest_id()
        if latest is None:
            return []
        versions: List[int] = []
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is None:
                continue
            if entry.state in states:
                versions.append(entry.log_version)
            if entry.state in (States.CREATING, States.VACUUMING) and log_id != latest:
                break
        return versions

    def create_latest_stable_log(self, log_id: int) -> bool:
        entry = self.get_log(log_id)
        if entry is None or entry.state not in STABLE_STATES:
            return False
        file_utils.atomic_overwrite(
            self._latest_stable_path, json_utils.to_json(entry.to_json_dict()))
        return True

    def delete_latest_stable_log(self) -> bool:
        try:
            if os.path.exists(self._latest_stable_path):
                os.unlink(self._latest_stable_path)
            return True
        except OSError:
            return False

    def write_log(self, log_id: int, entry: IndexLogEntry) -> bool:
        """Write entry at ``log_id`` iff that id doesn't exist yet."""
        entry.id = log_id
        return file_utils.atomic_create(
            self._path_from_id(log_id), json_utils.to_json(entry.to_json_dict()))
