"""Versioned operation log with optimistic concurrency.

Parity reference: index/IndexLogManager.scala:33-185. Layout under an index's
root path:

    <indexPath>/_hyperspace_log/<id>        — JSON log entry, immutable
    <indexPath>/_hyperspace_log/latestStable — copy of the latest stable entry

``write_log`` refuses to overwrite an existing id (conditional
put-if-absent), which is how concurrent actions detect conflicts. The
storage behind the protocol is pluggable (log_store.LogStore): local FS
by default, conditional-put object stores by scheme registration — the
protocol uses no rename, so S3/GCS-class stores satisfy it with one
conditional PUT (SURVEY §7 hard-part 4; tests/test_log_store.py runs
the lifecycle against the object-store double).
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..robustness import fault_names as _fn
from ..robustness import faults as _faults
from ..robustness import retry as _retry
from ..util import json_utils
from .constants import IndexConstants, STABLE_STATES, States
from .log_entry import IndexLogEntry
from .log_store import (LocalFsLogStore, LogStore, store_for_path,
                        strip_file_scheme)


class IndexLogManager:
    def __init__(self, index_path: str, store: Optional[LogStore] = None):
        self._store = store or store_for_path(index_path)
        if isinstance(self._store, LocalFsLogStore):
            # Local store: a file:// URI must become a real path before
            # os.path.join builds entry paths under it.
            index_path = strip_file_scheme(index_path)
        self._index_path = index_path
        self._log_path = os.path.join(index_path, IndexConstants.HYPERSPACE_LOG)
        self._latest_stable_path = os.path.join(
            self._log_path, IndexConstants.LATEST_STABLE_LOG_NAME)

    @property
    def index_path(self) -> str:
        return self._index_path

    def _path_from_id(self, log_id: int) -> str:
        return os.path.join(self._log_path, str(log_id))

    def _get_log_at(self, path: str) -> Optional[IndexLogEntry]:
        data = self._store.read(path)
        if data is None:
            return None
        return IndexLogEntry.from_json(data)

    def get_log(self, log_id: int) -> Optional[IndexLogEntry]:
        return self._get_log_at(self._path_from_id(log_id))

    def get_latest_id(self) -> Optional[int]:
        ids = self._store.list_numeric_ids(self._log_path)
        return max(ids) if ids else None

    def get_latest_log(self) -> Optional[IndexLogEntry]:
        latest = self.get_latest_id()
        return self.get_log(latest) if latest is not None else None

    def _get_log_lenient(self, log_id: int) -> Optional[IndexLogEntry]:
        """get_log that treats an unparseable entry (torn write from a
        crash mid-rename window) as absent — only the recovery scan may be
        this forgiving; normal reads should surface corruption."""
        try:
            return self.get_log(log_id)
        except (ValueError, KeyError, TypeError):
            return None

    def get_latest_stable_log(self) -> Optional[IndexLogEntry]:
        """Latest entry in a STABLE state; falls back to a backward scan past a
        broken tail — including an unparseable (torn) tail entry
        (reference: IndexLogManager.scala:93-117)."""
        try:
            log = self._get_log_at(self._latest_stable_path)
        except (ValueError, KeyError, TypeError):
            log = None
        if log is not None and log.state not in STABLE_STATES:
            # A stale/invalid latestStable (e.g. crash between write_log and
            # create_latest_stable_log); fall back to the backward scan.
            log = None
        if log is None:
            latest = self.get_latest_id()
            if latest is not None:
                for log_id in range(latest, -1, -1):
                    entry = self._get_log_lenient(log_id)
                    if entry is not None and entry.state in STABLE_STATES:
                        return entry
                    if entry is not None and entry.state in (
                            States.CREATING, States.VACUUMING):
                        # Logs before a CREATING/VACUUMING entry are unrelated.
                        return None
            return None
        return log

    def get_index_versions(self, states: List[str]) -> List[int]:
        """Index log versions whose state is in ``states``, newest first,
        stopping at the most recent CREATING/VACUUMING boundary."""
        latest = self.get_latest_id()
        if latest is None:
            return []
        versions: List[int] = []
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is None:
                continue
            if entry.state in states:
                versions.append(entry.log_version)
            if entry.state in (States.CREATING, States.VACUUMING) and log_id != latest:
                break
        return versions

    def latest_entry_fingerprint(self) -> Optional[tuple]:
        """(latest id, md5 of the latest entry's raw bytes), or None when
        the log is empty. Cheap change detector for the serving result
        cache: a full refresh restarts the log at the SAME ids (fresh
        create cycle), so the id alone cannot pin the index state — the
        entry bytes can, without parsing JSON."""
        latest = self.get_latest_id()
        if latest is None:
            return None
        data = self._store.read(self._path_from_id(latest))
        from ..util import hashing
        return (latest, hashing.md5_hex(data) if data is not None else "")

    def create_latest_stable_log(self, log_id: int) -> bool:
        entry = self.get_log(log_id)
        if entry is None or entry.state not in STABLE_STATES:
            return False
        data = json_utils.to_json(entry.to_json_dict())

        def _put() -> None:
            # Crash window the recovery scan must survive: a kill here
            # leaves the final entry committed but latestStable stale —
            # get_latest_stable_log's backward scan covers it. Transient
            # store errors (OSError on a flaky mount / object store)
            # retry with backoff; the cache is last-writer-wins, so a
            # re-put is always safe.
            _faults.fault_point(_fn.LOG_STABLE)
            self._store.put_overwrite(self._latest_stable_path, data)

        _retry.call(_put, where="log.stable")
        return True

    def delete_latest_stable_log(self) -> bool:
        return self._store.delete(self._latest_stable_path)

    def write_log(self, log_id: int, entry: IndexLogEntry) -> bool:
        """Write entry at ``log_id`` iff that id doesn't exist yet.
        Transient store errors retry (robustness/retry.py): put-if-absent
        decides every race, so re-putting after an OSError keeps exactly
        the protocol's semantics — a retry that loses the race reports
        False like any other loser. One subtlety makes the retry
        outcome-idempotent: a failed attempt may have COMMITTED the
        entry before erroring (e.g. link-into-place succeeded, the temp
        cleanup raised), so a post-transient "loss" whose stored bytes
        are OUR bytes is a win, not a conflict. The fault point inside
        the retried body is where the crash harness kill -9s
        mid-protocol."""
        entry.id = log_id
        path = self._path_from_id(log_id)
        data = json_utils.to_json(entry.to_json_dict())
        state = {"transient": False}

        def _put() -> bool:
            _faults.fault_point(_fn.LOG_WRITE)
            try:
                return self._store.put_if_absent(path, data)
            except _retry.TRANSIENT_TYPES:
                state["transient"] = True
                raise

        won = _retry.call(_put, where="log.write")
        if not won and state["transient"]:
            won = self._store.read(path) == data  # lost to OURSELVES?
        return won
