"""IndexManager interface + implementations.

Parity reference: index/IndexManager.scala:24-125 (the CRUD contract),
index/IndexCollectionManager.scala:28-196 (dispatch to actions with
per-index log/data managers; list indexes by scanning the system path),
index/CachingIndexCollectionManager.scala:38-170 (TTL cache over getIndexes,
cleared on any mutation).
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..exceptions import HyperspaceException
from ..util import file_utils
from .cache import CreationTimeBasedIndexCache
from .constants import IndexConstants, States
from .data_manager import IndexDataManager
from .log_entry import IndexLogEntry
from .log_manager import IndexLogManager
from .path_resolver import PathResolver


class IndexManager:
    """The CRUD contract (reference: IndexManager.scala:24-125)."""

    def create(self, df, index_config) -> None:
        raise NotImplementedError

    def delete(self, index_name: str) -> None:
        raise NotImplementedError

    def restore(self, index_name: str) -> None:
        raise NotImplementedError

    def vacuum(self, index_name: str) -> None:
        raise NotImplementedError

    def refresh(self, index_name: str, mode: str) -> None:
        raise NotImplementedError

    def optimize(self, index_name: str, mode: str) -> None:
        raise NotImplementedError

    def cancel(self, index_name: str) -> None:
        raise NotImplementedError

    def indexes(self):
        """User-facing statistics rows as a pandas DataFrame."""
        raise NotImplementedError

    def get_indexes(self, states: Optional[List[str]] = None) -> List[IndexLogEntry]:
        raise NotImplementedError

    def get_index(self, index_name: str) -> Optional[IndexLogEntry]:
        raise NotImplementedError

    def get_index_versions(self, index_name: str,
                           states: List[str]) -> List[int]:
        raise NotImplementedError


class IndexCollectionManager(IndexManager):
    def __init__(self, session):
        self.session = session
        self._path_resolver = PathResolver(session.hs_conf)

    # ------------------------------------------------------------------
    # Helpers (parity: IndexCollectionManager.withLogManager).
    # ------------------------------------------------------------------

    def _index_path(self, name: str) -> str:
        return self._path_resolver.get_index_path(name)

    def _log_manager(self, name: str, must_exist: bool = True) -> IndexLogManager:
        path = self._index_path(name)
        if must_exist and not file_utils.is_dir(path):
            raise HyperspaceException(f"Index with name {name} could not be found.")
        return IndexLogManager(path)

    def _data_manager(self, name: str) -> IndexDataManager:
        return IndexDataManager(self._index_path(name))

    def log_manager_for(self, name: str) -> IndexLogManager:
        """Public accessor for an index's op-log manager (used by the
        versioned-source rules for time-travel index version selection)."""
        return self._log_manager(name)

    # ------------------------------------------------------------------
    # CRUD dispatch.
    # ------------------------------------------------------------------

    def create(self, df, index_config) -> None:
        from ..api import DataSkippingIndexConfig
        name = index_config.index_name
        log_mgr = self._log_manager(name, must_exist=False)
        if isinstance(index_config, DataSkippingIndexConfig):
            from ..actions.create_skipping import CreateDataSkippingAction
            action_cls = CreateDataSkippingAction
        else:
            from ..actions.create import CreateAction
            action_cls = CreateAction
        action_cls(self.session, df, index_config, log_mgr,
                   self._data_manager(name)).run()

    def delete(self, index_name: str) -> None:
        from ..actions.lifecycle import DeleteAction
        DeleteAction(self.session, self._log_manager(index_name)).run()

    def restore(self, index_name: str) -> None:
        from ..actions.lifecycle import RestoreAction
        RestoreAction(self.session, self._log_manager(index_name)).run()

    def vacuum(self, index_name: str) -> None:
        from ..actions.lifecycle import VacuumAction
        VacuumAction(self.session, self._log_manager(index_name),
                     self._data_manager(index_name)).run()

    def cancel(self, index_name: str) -> None:
        from ..actions.lifecycle import CancelAction
        CancelAction(self.session, self._log_manager(index_name)).run()

    def refresh(self, index_name: str, mode: str = "full") -> None:
        if mode not in IndexConstants.REFRESH_MODES:
            raise HyperspaceException(
                f"Unsupported refresh mode: {mode}; "
                f"choose from {IndexConstants.REFRESH_MODES}")
        log_mgr = self._log_manager(index_name)
        latest = log_mgr.get_latest_stable_log()
        if latest is not None \
                and latest.derivedDataset.kind == "DataSkippingIndex":
            from ..actions.create_skipping import (
                RefreshDataSkippingAction, RefreshDataSkippingIncrementalAction)
            if mode == IndexConstants.REFRESH_MODE_QUICK:
                raise HyperspaceException(
                    "Quick refresh is not supported for data-skipping "
                    "indexes; use full or incremental.")
            cls = {
                IndexConstants.REFRESH_MODE_FULL: RefreshDataSkippingAction,
                IndexConstants.REFRESH_MODE_INCREMENTAL:
                    RefreshDataSkippingIncrementalAction,
            }[mode]
        else:
            from ..actions.refresh import (RefreshAction,
                                           RefreshIncrementalAction,
                                           RefreshQuickAction)
            cls = {
                IndexConstants.REFRESH_MODE_FULL: RefreshAction,
                IndexConstants.REFRESH_MODE_INCREMENTAL: RefreshIncrementalAction,
                IndexConstants.REFRESH_MODE_QUICK: RefreshQuickAction,
            }[mode]
        cls(self.session, log_mgr, self._data_manager(index_name)).run()

    def optimize(self, index_name: str, mode: str = "quick") -> None:
        from ..actions.optimize import OptimizeAction
        if mode not in IndexConstants.OPTIMIZE_MODES:
            raise HyperspaceException(
                f"Unsupported optimize mode: {mode}; "
                f"choose from {IndexConstants.OPTIMIZE_MODES}")
        OptimizeAction(self.session, self._log_manager(index_name),
                       self._data_manager(index_name), mode).run()

    # ------------------------------------------------------------------
    # Listing.
    # ------------------------------------------------------------------

    def _index_names(self) -> List[str]:
        system_path = self._path_resolver.system_path
        if not file_utils.is_dir(system_path):
            return []
        return sorted(
            n for n in file_utils.list_dir(system_path)
            if file_utils.is_dir(
                os.path.join(system_path, n, IndexConstants.HYPERSPACE_LOG)))

    def get_indexes(self, states: Optional[List[str]] = None) -> List[IndexLogEntry]:
        out = []
        for name in self._index_names():
            entry = IndexLogManager(
                os.path.join(self._path_resolver.system_path, name)).get_latest_log()
            if entry is not None and (states is None or entry.state in states):
                out.append(entry)
        return out

    def get_index(self, index_name: str) -> Optional[IndexLogEntry]:
        if index_name not in self._index_names():
            return None
        return self._log_manager(index_name).get_latest_log()

    def latest_log_ids(self) -> tuple:
        """(index name, latest op-log id, entry-bytes md5) per index under
        the system path, name-sorted — the result cache's invalidation
        component (serving/fingerprint.py). Reads directory listings plus
        the one latest entry file (no JSON parse) and deliberately
        bypasses the TTL metadata cache: a cross-process refresh must
        flip cache keys at once."""
        out = []
        for name in self._index_names():
            fp = IndexLogManager(os.path.join(
                self._path_resolver.system_path,
                name)).latest_entry_fingerprint()
            if fp is not None:
                out.append((name, fp[0], fp[1]))
        return tuple(out)

    def get_index_versions(self, index_name: str, states: List[str]) -> List[int]:
        return self._log_manager(index_name).get_index_versions(states)

    def indexes(self):
        from .statistics import IndexStatistics
        import pandas as pd
        counts = self.session._index_usage_counts
        rows = [IndexStatistics.from_entry(
                    e, usage_count=counts.get(e.name, 0)).to_row()
                for e in self.get_indexes()
                if e.state != States.DOESNOTEXIST]
        return pd.DataFrame(rows, columns=IndexStatistics.SUMMARY_COLUMNS)


class CachingIndexCollectionManager(IndexCollectionManager):
    """TTL-cached getIndexes; every mutation clears the cache
    (parity: CachingIndexCollectionManager.scala:38-124)."""

    def __init__(self, session):
        super().__init__(session)
        self._cache = CreationTimeBasedIndexCache(
            session.hs_conf.index_cache_expiry_seconds)

    def clear_cache(self) -> None:
        self._cache.clear()

    def get_indexes(self, states: Optional[List[str]] = None) -> List[IndexLogEntry]:
        if states is None:
            return super().get_indexes(None)
        all_entries = self._cache.get()
        if all_entries is None:
            all_entries = super().get_indexes(None)
            self._cache.set(all_entries)
        return [e for e in all_entries if e.state in states]

    def create(self, df, index_config) -> None:
        self.clear_cache()
        super().create(df, index_config)

    def delete(self, index_name: str) -> None:
        self.clear_cache()
        super().delete(index_name)

    def restore(self, index_name: str) -> None:
        self.clear_cache()
        super().restore(index_name)

    def vacuum(self, index_name: str) -> None:
        self.clear_cache()
        super().vacuum(index_name)

    def refresh(self, index_name: str, mode: str = "full") -> None:
        self.clear_cache()
        super().refresh(index_name, mode)

    def optimize(self, index_name: str, mode: str = "quick") -> None:
        self.clear_cache()
        super().optimize(index_name, mode)

    def cancel(self, index_name: str) -> None:
        self.clear_cache()
        super().cancel(index_name)
