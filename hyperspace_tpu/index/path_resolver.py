"""Resolves index names to paths under the system path.

Parity reference: index/PathResolver.scala:39.
"""

from __future__ import annotations

import os
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ..config import HyperspaceConf


class PathResolver:
    def __init__(self, conf: "HyperspaceConf"):
        self._conf = conf

    @property
    def system_path(self) -> str:
        return self._conf.system_path()

    def get_index_path(self, name: str) -> str:
        return os.path.join(self.system_path, name)
