"""User-facing index statistics rows (parity: index/IndexStatistics.scala:43-196)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

from .log_entry import IndexLogEntry


@dataclass
class IndexStatistics:
    name: str
    indexed_columns: List[str]
    included_columns: List[str]
    num_buckets: int
    schema_json: str
    index_location: str
    state: str
    lineage_enabled: bool
    source_file_count: int
    source_size_bytes: int
    index_file_count: int
    index_size_bytes: int
    appended_file_count: int
    deleted_file_count: int
    index_content_paths: List[str]
    # Times a real (non-diagnostic) rewrite pass selected this index in
    # THIS session: executions and explicit optimized_plan() calls, the
    # same passes that emit usage telemetry; explain/why_not/what_if run
    # silent and never count (rule_utils.log_index_usage tally; 0 across
    # sessions/processes) — the advisor's and humans' dead-index signal.
    usage_count: int = 0

    SUMMARY_COLUMNS = ["name", "indexedColumns", "includedColumns", "numBuckets",
                       "schema", "indexLocation", "state", "usageCount"]

    @staticmethod
    def from_entry(entry: IndexLogEntry,
                   usage_count: int = 0) -> "IndexStatistics":
        import json
        content_files = entry.content.files
        # Index location = common version dir prefix of the newest files.
        location = ""
        if content_files:
            import os
            location = os.path.dirname(sorted(content_files)[-1])
        return IndexStatistics(
            name=entry.name,
            indexed_columns=list(entry.indexed_columns),
            included_columns=list(entry.included_columns),
            num_buckets=entry.num_buckets,
            schema_json=json.dumps(entry.schema.to_json_dict()),
            index_location=location,
            state=entry.state,
            lineage_enabled=entry.has_lineage_column(),
            source_file_count=len(entry.source_file_info_set),
            source_size_bytes=entry.source_files_size_in_bytes,
            index_file_count=len(entry.content.file_infos),
            index_size_bytes=entry.index_files_size_in_bytes,
            appended_file_count=len(entry.appended_files),
            deleted_file_count=len(entry.deleted_files),
            index_content_paths=sorted({p.rsplit("/", 1)[0] for p in content_files}),
            usage_count=usage_count)

    def to_row(self) -> Dict:
        return {
            "name": self.name,
            "indexedColumns": self.indexed_columns,
            "includedColumns": self.included_columns,
            "numBuckets": self.num_buckets,
            "schema": self.schema_json,
            "indexLocation": self.index_location,
            "state": self.state,
            "usageCount": self.usage_count,
        }

    def to_extended_row(self) -> Dict:
        row = self.to_row()
        row.update({
            "lineageEnabled": self.lineage_enabled,
            "sourceFileCount": self.source_file_count,
            "sourceSizeBytes": self.source_size_bytes,
            "indexFileCount": self.index_file_count,
            "indexSizeBytes": self.index_size_bytes,
            "appendedFileCount": self.appended_file_count,
            "deletedFileCount": self.deleted_file_count,
            "indexContentPaths": self.index_content_paths,
        })
        return row
