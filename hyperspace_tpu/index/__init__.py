from .constants import IndexConstants, STABLE_STATES, States  # noqa: F401
from .data_manager import IndexDataManager  # noqa: F401
from .log_entry import (  # noqa: F401
    Content, CoveringIndex, DataSkippingIndex, Directory, FileIdTracker, FileInfo, Hdfs,
    IndexLogEntry, LogEntry, LogicalPlanFingerprint, Relation, Signature, Sketch, Source,
    SourcePlan, Update)
from .log_manager import IndexLogManager  # noqa: F401
from .path_resolver import PathResolver  # noqa: F401
