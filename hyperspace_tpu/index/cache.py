"""TTL cache for index metadata (parity: index/Cache.scala:23,
CachingIndexCollectionManager.scala:61-124, IndexCacheFactory.scala)."""

from __future__ import annotations

import time
from typing import Generic, Optional, TypeVar

T = TypeVar("T")


class Cache(Generic[T]):
    def get(self) -> Optional[T]:
        raise NotImplementedError

    def set(self, entry: T) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError


class CreationTimeBasedIndexCache(Cache[T]):
    """Entries expire ``expiry_seconds`` after they were cached."""

    def __init__(self, expiry_seconds_fn):
        # Callable so the TTL tracks the live conf value.
        self._expiry_seconds_fn = expiry_seconds_fn
        self._entry: Optional[T] = None
        self._cached_at: float = 0.0

    def get(self) -> Optional[T]:
        if self._entry is None:
            return None
        if time.time() - self._cached_at > self._expiry_seconds_fn():
            self.clear()
            return None
        return self._entry

    def set(self, entry: T) -> None:
        self._entry = entry
        self._cached_at = time.time()

    def clear(self) -> None:
        self._entry = None
        self._cached_at = 0.0
