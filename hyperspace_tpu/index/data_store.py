"""Object-store residency for index DATA files (VERDICT r5 #7).

``log_store.py`` proved the OP LOG rename-free; this module extends the
same stance to the index data files and the collection manager's
directory-existence gates, so the ENTIRE index lifecycle
(create/refresh/optimize/vacuum and the query-side reads) can run
against an object store. The reference runs wholly on HDFS-compatible
stores incl. ABFS/S3A (index/IndexLogManager.scala:33,
docs/_docs/14-toh-indexes-on-the-lake.md); the TPU-native runtime
targets object stores directly through pyarrow's ``filesystem=``
parameter, which accepts any fsspec-style filesystem — so a deployment
backs a scheme with one ``register_scheme`` call and every parquet
write, leaf listing, existence gate, and recursive delete routes
through it. Nothing in the data path needs rename: data files are
immutable puts under fresh ``v__=<n>/`` names, listings are prefix
LISTs, deletes are prefix deletes.

Paths without a scheme (or ``file://``) keep the local-filesystem fast
path untouched. The built-in ``hsmem://`` scheme (fsspec's memory
filesystem + a lock-guarded conditional-put log adapter) is the test
double proving the whole lifecycle runs store-only — the analogue of
``log_store.InMemoryObjectStore`` for the data side.
"""

from __future__ import annotations

import posixpath
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..exceptions import HyperspaceException


class DataStore:
    """Index-data storage contract: immutable file puts + prefix lists.

    ``filesystem()`` returns an fsspec-style filesystem handed straight
    to pyarrow (``pq.write_table(..., filesystem=...)``); the remaining
    operations cover the non-parquet surface (existence gates, leaf
    listing for Content fingerprints, recursive delete for vacuum)."""

    def filesystem(self):
        raise NotImplementedError

    def normalize(self, path: str) -> str:
        """The path as ``filesystem()`` expects it (scheme stripped)."""
        raise NotImplementedError

    def is_dir(self, path: str) -> bool:
        raise NotImplementedError

    def list_dir(self, path: str) -> List[str]:
        """Names (not paths) directly under ``path``."""
        raise NotImplementedError

    def list_leaf_files(self, path: str) -> List[str]:
        """All regular files under ``path`` recursively — SCHEME-QUALIFIED
        full paths (they round-trip into log entries and back into
        reads), sorted, hidden names excluded."""
        raise NotImplementedError

    def file_info(self, path: str) -> Tuple[str, int, int]:
        """(path, size, mtime_ms) — the signature triple."""
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        """Create a directory marker (no-op on flat object stores)."""
        raise NotImplementedError

    def delete_recursively(self, path: str) -> None:
        raise NotImplementedError


class InMemoryDataStore(DataStore):
    """fsspec memory filesystem behind ``hsmem://`` paths. The memory
    filesystem is process-global (fsspec singleton), so distinct tests
    isolate by path root exactly as they do with tmp dirs."""

    scheme = "hsmem"

    def __init__(self):
        import fsspec
        self._fs = fsspec.filesystem("memory")

    def filesystem(self):
        return self._fs

    def normalize(self, path: str) -> str:
        if path.startswith(self.scheme + "://"):
            return "/" + path[len(self.scheme) + 3:].lstrip("/")
        return path

    def _qualify(self, norm: str) -> str:
        return f"{self.scheme}://{norm.lstrip('/')}"

    def is_dir(self, path: str) -> bool:
        p = self.normalize(path)
        try:
            return self._fs.isdir(p)
        except FileNotFoundError:
            return False

    def list_dir(self, path: str) -> List[str]:
        p = self.normalize(path)
        if not self.is_dir(p):
            return []
        return sorted(posixpath.basename(e.rstrip("/"))
                      for e in self._fs.ls(p, detail=False))

    def list_leaf_files(self, path: str) -> List[str]:
        p = self.normalize(path)
        if not self._fs.exists(p):
            return []
        root = p.strip("/")
        out = []
        for f in self._fs.find(p):
            # Hidden-name filter applies only BELOW the listing root
            # (matching the local os.walk behavior — an ancestor segment
            # like '_data' in the index root must not hide everything).
            rel = f.strip("/")
            if rel.startswith(root):
                rel = rel[len(root):].lstrip("/")
            if any(s.startswith((".", "_")) for s in rel.split("/")):
                continue
            out.append(self._qualify(f))
        return sorted(out)

    def file_info(self, path: str) -> Tuple[str, int, int]:
        p = self.normalize(path)
        info = self._fs.info(p)
        created = info.get("created") or 0
        try:
            mtime_ms = int(float(created) * 1000)
        except (TypeError, ValueError):
            mtime_ms = 0
        return (path, int(info.get("size") or 0), mtime_ms)

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(self.normalize(path), exist_ok=True)

    def delete_recursively(self, path: str) -> None:
        p = self.normalize(path)
        if self._fs.exists(p):
            self._fs.rm(p, recursive=True)


_SCHEME_FACTORIES: Dict[str, Callable[[], DataStore]] = {}
_STORE_CACHE: Dict[str, DataStore] = {}
_LOCK = threading.Lock()


def register_scheme(scheme: str, factory: Callable[[], DataStore]) -> None:
    """Back ``scheme://`` index-data paths with a custom DataStore."""
    _SCHEME_FACTORIES[scheme.lower()] = factory


def scheme_of(path: str) -> Optional[str]:
    if "://" not in path:
        return None
    scheme = path.split("://", 1)[0].lower()
    return None if scheme in ("file", "") else scheme


def store_for_path(path: str) -> Optional[DataStore]:
    """The DataStore for a scheme-qualified path, or None for local
    paths (the default fast path — untouched local-FS behavior)."""
    scheme = scheme_of(path)
    if scheme is None:
        return None
    with _LOCK:
        store = _STORE_CACHE.get(scheme)
        if store is None:
            factory = _SCHEME_FACTORIES.get(scheme)
            if factory is None:
                raise HyperspaceException(
                    f"No DataStore registered for scheme {scheme!r}; "
                    "register one with hyperspace_tpu.index.data_store."
                    "register_scheme (pyarrow-compatible fsspec filesystem "
                    "+ prefix listing — see the module docstring)")
            store = factory()
            _STORE_CACHE[scheme] = store
    return store


def fs_and_path(path: str):
    """(filesystem-or-None, normalized path) for pyarrow IO calls.
    Local paths return (None, path): pyarrow resolves them natively."""
    store = store_for_path(path)
    if store is None:
        return None, path
    return store.filesystem(), store.normalize(path)


# ---------------------------------------------------------------------------
# The built-in in-memory scheme + its op-log adapter.
# ---------------------------------------------------------------------------

class _MemConditionalPutLogStore:
    """Conditional-put LogStore over the same fsspec memory filesystem
    the data side uses, so a single ``hsmem://`` tree carries the whole
    index (log + data). The lock stands in for the store's conditional
    PUT (S3 If-None-Match: *) — this is the test double; real stores
    register adapters speaking their native precondition."""

    def __init__(self):
        import fsspec
        self._fs = fsspec.filesystem("memory")
        self._lock = threading.Lock()

    @staticmethod
    def _norm(path: str) -> str:
        return "/" + path[len("hsmem://"):].lstrip("/") \
            if path.startswith("hsmem://") else path

    def put_if_absent(self, path: str, data: str) -> bool:
        p = self._norm(path)
        with self._lock:
            if self._fs.exists(p):
                return False
            with self._fs.open(p, "w") as f:
                f.write(data)
            return True

    def put_overwrite(self, path: str, data: str) -> None:
        p = self._norm(path)
        with self._lock:
            with self._fs.open(p, "w") as f:
                f.write(data)

    def read(self, path: str) -> Optional[str]:
        p = self._norm(path)
        with self._lock:
            if not self._fs.exists(p) or self._fs.isdir(p):
                return None
            with self._fs.open(p, "r") as f:
                return f.read()

    def list_numeric_ids(self, dirpath: str) -> List[int]:
        p = self._norm(dirpath)
        with self._lock:
            if not self._fs.exists(p):
                return []
            out = []
            for e in self._fs.ls(p, detail=False):
                tail = posixpath.basename(e.rstrip("/"))
                if tail.isdigit():
                    out.append(int(tail))
            return out

    def delete(self, path: str) -> bool:
        p = self._norm(path)
        with self._lock:
            if self._fs.exists(p):
                self._fs.rm(p)
            return True


def _register_builtin() -> None:
    from . import log_store
    register_scheme(InMemoryDataStore.scheme, InMemoryDataStore)
    log_store.register_scheme(InMemoryDataStore.scheme,
                              lambda path: _MemConditionalPutLogStore())


_register_builtin()
