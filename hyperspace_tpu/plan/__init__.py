from . import expr  # noqa: F401
from .nodes import (  # noqa: F401
    Aggregate, BucketSpec, BucketUnion, Filter, IndexScan, Join, Limit, LogicalPlan,
    Project, Scan, Sort, Union, infer_dtype)
