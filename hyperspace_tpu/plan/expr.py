"""Expression tree for the logical plan IR.

The reference delegates expressions to Spark Catalyst; this is our minimal,
columnar, XLA-friendly equivalent. Every expression evaluates to a whole
column (vectorized) — there is no row-at-a-time path, matching how XLA wants
the work batched.

Supported surface (driven by the reference's rule requirements + TPC-H):
column refs, literals, comparisons, boolean algebra, IN-lists, arithmetic,
and aggregate functions (Sum/Count/Min/Max/Avg).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

from ..exceptions import HyperspaceException


class Expr:
    """Base class. Operators build trees; `references` lists column names."""

    def __eq__(self, other):  # == builds an expression, not a bool.
        return EqualTo(self, _wrap(other))

    def __ne__(self, other):
        return Not(EqualTo(self, _wrap(other)))

    def __lt__(self, other):
        return LessThan(self, _wrap(other))

    def __le__(self, other):
        return LessThanOrEqual(self, _wrap(other))

    def __gt__(self, other):
        return GreaterThan(self, _wrap(other))

    def __ge__(self, other):
        return GreaterThanOrEqual(self, _wrap(other))

    def __and__(self, other):
        return And(self, _wrap(other))

    def __or__(self, other):
        return Or(self, _wrap(other))

    def __invert__(self):
        return Not(self)

    def __add__(self, other):
        return Add(self, _wrap(other))

    def __radd__(self, other):
        return Add(_wrap(other), self)

    def __sub__(self, other):
        return Subtract(self, _wrap(other))

    def __rsub__(self, other):
        return Subtract(_wrap(other), self)

    def __mul__(self, other):
        return Multiply(self, _wrap(other))

    def __rmul__(self, other):
        return Multiply(_wrap(other), self)

    def __truediv__(self, other):
        return Divide(self, _wrap(other))

    def __hash__(self):
        return hash(repr(self))

    def isin(self, values: Sequence[Any]):
        return In(self, [_wrap(v) for v in values])

    def between(self, low, high):
        return And(GreaterThanOrEqual(self, _wrap(low)),
                   LessThanOrEqual(self, _wrap(high)))

    def alias(self, name: str):
        return Alias(self, name)

    def like(self, pattern: str):
        """SQL LIKE (% = any run, _ = any one char), full-string match."""
        return Like(self, pattern)

    def is_null(self):
        return IsNull(self)

    def is_not_null(self):
        return IsNull(self, negated=True)

    def substr(self, start: int, length: Optional[int] = None):
        """SQL SUBSTRING: 1-based ``start``, optional ``length``."""
        return Substring(self, start, length)

    @property
    def references(self) -> List[str]:
        out: List[str] = []
        for c in self.children:
            out.extend(c.references)
        # De-dup preserving order.
        seen = set()
        return [x for x in out if not (x in seen or seen.add(x))]

    @property
    def children(self) -> List["Expr"]:
        return []

    @property
    def name(self) -> str:
        """Output column name when projected."""
        return repr(self)


def _wrap(v) -> "Expr":
    if isinstance(v, Expr):
        return v
    return Lit(v)


@dataclass(frozen=True, eq=False, repr=False)
class Col(Expr):
    column: str

    @property
    def references(self) -> List[str]:
        return [self.column]

    @property
    def name(self) -> str:
        return self.column

    def __repr__(self):
        return f"col({self.column})"


@dataclass(frozen=True, eq=False, repr=False)
class Lit(Expr):
    value: Any

    def __post_init__(self):
        v = self.value
        if isinstance(v, datetime.date) and not isinstance(v, datetime.datetime):
            object.__setattr__(self, "value", v)
        elif not isinstance(v, (int, float, bool, str, type(None))):
            raise HyperspaceException(f"Unsupported literal type: {type(v)}")

    def __repr__(self):
        return f"lit({self.value!r})"


class _Binary(Expr):
    op_name = "?"
    symbol = "?"

    def __init__(self, left: Expr, right: Expr):
        self.left = left
        self.right = right

    @property
    def children(self) -> List[Expr]:
        return [self.left, self.right]

    def __repr__(self):
        return f"({self.left!r} {self.symbol} {self.right!r})"


class EqualTo(_Binary):
    op_name, symbol = "EqualTo", "="


class LessThan(_Binary):
    op_name, symbol = "LessThan", "<"


class LessThanOrEqual(_Binary):
    op_name, symbol = "LessThanOrEqual", "<="


class GreaterThan(_Binary):
    op_name, symbol = "GreaterThan", ">"


class GreaterThanOrEqual(_Binary):
    op_name, symbol = "GreaterThanOrEqual", ">="


class And(_Binary):
    op_name, symbol = "And", "AND"


class Or(_Binary):
    op_name, symbol = "Or", "OR"


class Add(_Binary):
    op_name, symbol = "Add", "+"


class Subtract(_Binary):
    op_name, symbol = "Subtract", "-"


class Multiply(_Binary):
    op_name, symbol = "Multiply", "*"


class Divide(_Binary):
    op_name, symbol = "Divide", "/"


class Not(Expr):
    op_name = "Not"

    def __init__(self, child: Expr):
        self.child = child

    @property
    def children(self) -> List[Expr]:
        return [self.child]

    def __repr__(self):
        return f"NOT({self.child!r})"


class In(Expr):
    op_name = "In"

    def __init__(self, value: Expr, options: List[Expr]):
        self.value = value
        self.options = options

    @property
    def children(self) -> List[Expr]:
        return [self.value] + list(self.options)

    def __repr__(self):
        return f"{self.value!r} IN ({', '.join(map(repr, self.options))})"


@dataclass(frozen=True, eq=False, repr=False)
class Alias(Expr):
    child: Expr
    alias_name: str

    @property
    def children(self) -> List[Expr]:
        return [self.child]

    @property
    def name(self) -> str:
        return self.alias_name

    def __repr__(self):
        return f"{self.child!r} AS {self.alias_name}"


class Like(Expr):
    """SQL LIKE predicate. The reference inherits Spark's full expression
    surface (rules/FilterIndexRule.scala:165-186 matches ANY Filter
    condition); LIKE is the workhorse of TPC-H/TPC-DS string predicates
    (e.g. tpcds/queries' p_type filters). Evaluated over the
    order-preserving string dictionary, so the per-row cost is one gather.
    """

    op_name = "Like"

    def __init__(self, child: Expr, pattern: str, negated: bool = False):
        if not isinstance(pattern, str):
            raise HyperspaceException("LIKE pattern must be a string literal")
        self.child = child
        self.pattern = pattern
        self.negated = negated

    @property
    def children(self) -> List[Expr]:
        return [self.child]

    def __repr__(self):
        return (f"{self.child!r} {'NOT ' if self.negated else ''}"
                f"LIKE {self.pattern!r}")


class IsNull(Expr):
    """IS [NOT] NULL predicate (never yields null itself)."""

    op_name = "IsNull"

    def __init__(self, child: Expr, negated: bool = False):
        self.child = child
        self.negated = negated

    @property
    def children(self) -> List[Expr]:
        return [self.child]

    def __repr__(self):
        return f"{self.child!r} IS {'NOT ' if self.negated else ''}NULL"


class CaseWhen(Expr):
    """CASE WHEN c1 THEN v1 [WHEN ...]* [ELSE e] END. A null/false
    condition falls through; no matching branch and no ELSE yields null
    (SQL semantics)."""

    op_name = "CaseWhen"

    def __init__(self, branches: Sequence[Tuple[Expr, Expr]],
                 else_value: Optional[Expr] = None):
        if not branches:
            raise HyperspaceException("CASE requires at least one WHEN")
        self.branches = [(c, _wrap(v)) for c, v in branches]
        self.else_value = _wrap(else_value) if else_value is not None \
            and not isinstance(else_value, Expr) else else_value

    @property
    def children(self) -> List[Expr]:
        out: List[Expr] = []
        for c, v in self.branches:
            out.extend((c, v))
        if self.else_value is not None:
            out.append(self.else_value)
        return out

    def __repr__(self):
        parts = " ".join(f"WHEN {c!r} THEN {v!r}" for c, v in self.branches)
        tail = f" ELSE {self.else_value!r}" if self.else_value is not None else ""
        return f"CASE {parts}{tail} END"


_DATE_PARTS = ("year", "month", "day", "quarter")


class DatePart(Expr):
    """EXTRACT(part FROM date) — year/month/day/quarter as int64."""

    op_name = "DatePart"

    def __init__(self, part: str, child: Expr):
        part = part.lower()
        if part not in _DATE_PARTS:
            raise HyperspaceException(
                f"EXTRACT supports {_DATE_PARTS}, got {part!r}")
        self.part = part
        self.child = child

    @property
    def children(self) -> List[Expr]:
        return [self.child]

    def __repr__(self):
        return f"EXTRACT({self.part} FROM {self.child!r})"


class Substring(Expr):
    """SQL SUBSTRING with 1-based literal start/length (evaluated on the
    string dictionary, one re-encode + gather per column)."""

    op_name = "Substring"

    def __init__(self, child: Expr, start: int, length: Optional[int] = None):
        if not isinstance(start, int) or \
                (length is not None and not isinstance(length, int)):
            raise HyperspaceException(
                "SUBSTRING start/length must be integer literals")
        self.child = child
        self.start = start
        self.length = length

    @property
    def children(self) -> List[Expr]:
        return [self.child]

    def __repr__(self):
        tail = f", {self.length}" if self.length is not None else ""
        return f"SUBSTRING({self.child!r}, {self.start}{tail})"


class StringTransform(Expr):
    """UPPER/LOWER/TRIM — per-dictionary-entry host transform + gather."""

    _FNS = ("upper", "lower", "trim")
    op_name = "StringTransform"

    def __init__(self, fn: str, child: Expr):
        fn = fn.lower()
        if fn not in self._FNS:
            raise HyperspaceException(
                f"String function must be one of {self._FNS}, got {fn!r}")
        self.fn = fn
        self.child = child

    @property
    def children(self) -> List[Expr]:
        return [self.child]

    def __repr__(self):
        return f"{self.fn.upper()}({self.child!r})"


# ---------------------------------------------------------------------------
# Aggregates.
# ---------------------------------------------------------------------------

class Concat(Expr):
    """String concatenation with at most ONE column operand (the TPC-DS
    q5 ``concat('store', s_store_id)`` shape): evaluates as a pure
    dictionary rewrite — codes never change, the per-value strings do."""

    def __init__(self, parts: Sequence[Expr]):
        if sum(1 for p in parts if not isinstance(p, Lit)) > 1:
            raise HyperspaceException(
                "concat() supports at most one column operand "
                "(literal affixes rewrite the dictionary; general "
                "column-column concat would need a cross dictionary)")
        self.parts = list(parts)

    @property
    def children(self) -> List[Expr]:
        return list(self.parts)

    @property
    def name(self) -> str:
        return "concat(" + ", ".join(p.name for p in self.parts) + ")"

    def __repr__(self):
        return "concat(" + ", ".join(repr(p) for p in self.parts) + ")"


class NullLit(Expr):
    """A typed all-NULL constant column (the ROLLUP lowering's filler for
    rolled-up grouping keys; a bare ``Lit(None)`` has no type)."""

    def __init__(self, dtype: str):
        self.dtype = dtype

    @property
    def name(self) -> str:
        return f"null:{self.dtype}"

    def __repr__(self):
        return f"null({self.dtype})"


class Sqrt(Expr):
    """Square root (needed by the STDDEV lowering; the reference gets it
    from Spark SQL's function library)."""

    def __init__(self, child: Expr):
        self.child = child

    @property
    def children(self) -> List[Expr]:
        return [self.child]

    @property
    def name(self) -> str:
        return f"sqrt({self.child.name})"

    def __repr__(self):
        return f"sqrt({self.child!r})"


def sqrt(e) -> Sqrt:
    return Sqrt(_wrap(e))


class AggExpr(Expr):
    agg_name = "?"

    def __init__(self, child: Optional[Expr]):
        self.child = child

    @property
    def children(self) -> List[Expr]:
        return [self.child] if self.child is not None else []

    @property
    def name(self) -> str:
        inner = self.child.name if self.child is not None else "*"
        return f"{self.agg_name.lower()}({inner})"

    def __repr__(self):
        return self.name


class Sum(AggExpr):
    agg_name = "Sum"


class Count(AggExpr):
    agg_name = "Count"


class Min(AggExpr):
    agg_name = "Min"


class Max(AggExpr):
    agg_name = "Max"


class Avg(AggExpr):
    agg_name = "Avg"


class WindowExpr(Expr):
    """``fn([arg]) OVER (PARTITION BY p... [ORDER BY o [ASC|DESC]...]
    [frame])`` — the analytic-function marker the SQL front-end lowers to
    a Window plan node (the reference inherits these from Spark SQL; the
    TPC-DS corpus uses rank/sum/avg-over — e.g. queries q51/q53/q63/q89).

    ``fn``: 'rank' | 'dense_rank' | 'row_number' | 'sum' | 'avg' | 'min' |
    'max' | 'count'. ``frame``: 'partition' (whole partition — the SQL
    default without ORDER BY), 'range' (running aggregate including order
    peers — the default with ORDER BY), or 'rows' (running, row at a
    time — ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)."""

    RANK_FNS = ("rank", "dense_rank", "row_number")
    AGG_FNS = ("sum", "avg", "min", "max", "count")

    def __init__(self, fn: str, arg: Optional[Expr],
                 partition: Sequence[Expr],
                 orders: Sequence[Tuple[Expr, bool]],
                 frame: str = None):
        if fn not in self.RANK_FNS + self.AGG_FNS:
            raise HyperspaceException(f"Unknown window function {fn!r}")
        self.fn = fn
        self.arg = arg
        self.partition = list(partition)
        self.orders = [(e, bool(asc)) for e, asc in orders]
        if fn in self.RANK_FNS and not self.orders:
            raise HyperspaceException(
                f"window function {fn}() requires ORDER BY")
        if fn in ("sum", "avg", "min", "max") and arg is None:
            raise HyperspaceException(
                f"window function {fn}() requires an argument")
        if frame is None:
            frame = "range" if self.orders else "partition"
        if frame not in ("partition", "range", "rows"):
            raise HyperspaceException(f"Unknown window frame {frame!r}")
        self.frame = frame

    @property
    def children(self) -> List[Expr]:
        out = [] if self.arg is None else [self.arg]
        out.extend(self.partition)
        out.extend(e for e, _ in self.orders)
        return out

    @property
    def name(self) -> str:
        inner = "" if self.arg is None else self.arg.name
        return f"{self.fn}({inner}) OVER"

    def __repr__(self):
        parts = []
        if self.partition:
            parts.append("PARTITION BY "
                         + ", ".join(repr(p) for p in self.partition))
        if self.orders:
            parts.append("ORDER BY " + ", ".join(
                f"{e!r} {'ASC' if asc else 'DESC'}" for e, asc in self.orders))
        if self.frame == "rows":
            parts.append("ROWS UNBOUNDED PRECEDING")
        inner = "" if self.arg is None else repr(self.arg)
        return f"{self.fn}({inner}) OVER ({' '.join(parts)})"


def window(fn: str, arg=None, partition_by=(), order_by=(),
           frame: str = None) -> WindowExpr:
    """Public constructor: ``order_by`` items are exprs/names or
    (expr, ascending) pairs."""
    orders = []
    for o in order_by:
        if isinstance(o, tuple):
            e, asc = o
        else:
            e, asc = o, True
        orders.append((Col(e) if isinstance(e, str) else _wrap(e), asc))
    part = [Col(p) if isinstance(p, str) else _wrap(p) for p in partition_by]
    return WindowExpr(fn, None if arg is None else (
        Col(arg) if isinstance(arg, str) else _wrap(arg)), part, orders,
        frame)


class CountDistinct(AggExpr):
    """COUNT(DISTINCT child). Deliberately NOT a Count subclass: distinct
    counts are not decomposable (run partials cannot combine), so the
    two-phase and SPMD paths must not treat it as a plain count."""

    agg_name = "CountDistinct"

    def __init__(self, child: Expr):
        if child is None:
            raise ValueError("count_distinct requires a column expression")
        super().__init__(child)


# Public helpers (the pyspark-like functions module).

def col(name: str) -> Col:
    return Col(name)


def lit(value) -> Lit:
    return Lit(value)


def sum_(e) -> Sum:
    return Sum(_wrap(e) if not isinstance(e, Expr) else e)


def count(e=None) -> Count:
    return Count(_wrap(e) if e is not None and not isinstance(e, Expr) else e)


def min_(e) -> Min:
    return Min(_wrap(e) if not isinstance(e, Expr) else e)


def max_(e) -> Max:
    return Max(_wrap(e) if not isinstance(e, Expr) else e)


def avg(e) -> Avg:
    return Avg(_wrap(e) if not isinstance(e, Expr) else e)


def count_distinct(e) -> CountDistinct:
    if e is None:
        # count(None) means COUNT(*); the distinct analogue has no meaning.
        raise ValueError("count_distinct requires a column expression")
    return CountDistinct(_wrap(e) if not isinstance(e, Expr) else e)


def case_when(branches: Sequence[Tuple[Expr, Any]],
              else_value: Any = None) -> CaseWhen:
    return CaseWhen([(c, _wrap(v)) for c, v in branches],
                    _wrap(else_value) if else_value is not None else None)


def year(e) -> DatePart:
    return DatePart("year", _wrap(e))


def month(e) -> DatePart:
    return DatePart("month", _wrap(e))


def dayofmonth(e) -> DatePart:
    return DatePart("day", _wrap(e))


def quarter(e) -> DatePart:
    return DatePart("quarter", _wrap(e))


def substring(e, start: int, length: Optional[int] = None) -> Substring:
    return Substring(_wrap(e), start, length)


def upper(e) -> StringTransform:
    return StringTransform("upper", _wrap(e))


def lower(e) -> StringTransform:
    return StringTransform("lower", _wrap(e))


def trim(e) -> StringTransform:
    return StringTransform("trim", _wrap(e))


# ---------------------------------------------------------------------------
# Predicate utilities used by the rewrite rules.
# ---------------------------------------------------------------------------

def map_children(e: Expr, fn) -> Expr:
    """Rebuild ``e`` with every direct child replaced by ``fn(child)``.
    The single structural-rewrite primitive: rename_columns, the SQL
    front-end's alias resolution, and the rules' substitution walkers all
    ride on it, so a new Expr kind only needs one case here."""
    if isinstance(e, (Col, Lit, NullLit)):
        return e
    if isinstance(e, _Binary):
        return type(e)(fn(e.left), fn(e.right))
    if isinstance(e, Not):
        return Not(fn(e.child))
    if isinstance(e, In):
        return In(fn(e.value), [fn(o) for o in e.options])
    if isinstance(e, Alias):
        return Alias(fn(e.child), e.alias_name)
    if isinstance(e, Like):
        return Like(fn(e.child), e.pattern, e.negated)
    if isinstance(e, IsNull):
        return IsNull(fn(e.child), e.negated)
    if isinstance(e, CaseWhen):
        return CaseWhen([(fn(c), fn(v)) for c, v in e.branches],
                        fn(e.else_value) if e.else_value is not None else None)
    if isinstance(e, DatePart):
        return DatePart(e.part, fn(e.child))
    if isinstance(e, Substring):
        return Substring(fn(e.child), e.start, e.length)
    if isinstance(e, StringTransform):
        return StringTransform(e.fn, fn(e.child))
    if isinstance(e, Sqrt):
        return Sqrt(fn(e.child))
    if isinstance(e, Concat):
        return Concat([fn(p) for p in e.parts])
    if isinstance(e, AggExpr):
        if e.child is None:
            return e
        return type(e)(fn(e.child))
    if isinstance(e, WindowExpr):
        return WindowExpr(e.fn, None if e.arg is None else fn(e.arg),
                          [fn(p) for p in e.partition],
                          [(fn(o), asc) for o, asc in e.orders], e.frame)
    raise HyperspaceException(f"Cannot rewrite expression {e!r}")


def rename_columns(e: Expr, rename) -> Expr:
    """Rebuild ``e`` with every Col reference passed through ``rename``
    (a str -> str mapping). Used by the DataFrame API's case-insensitive
    resolution: user-spelled names are rewritten to the schema's spelling
    before plan construction. Nodes without Col descendants are returned
    as-is (Exprs are immutable, sharing is safe)."""
    if isinstance(e, Col):
        new = rename(e.column)
        return e if new == e.column else Col(new)
    return map_children(e, lambda c: rename_columns(c, rename))


def split_conjunctive_predicates(e: Expr) -> List[Expr]:
    """Flatten nested ANDs into a list (CNF top level)."""
    if isinstance(e, And):
        return split_conjunctive_predicates(e.left) + split_conjunctive_predicates(e.right)
    return [e]


def conjoin(parts: Sequence[Expr]) -> Expr:
    """Left-fold a non-empty predicate list back into one AND tree (the
    inverse of split_conjunctive_predicates for left-associated input)."""
    out = parts[0]
    for p in parts[1:]:
        out = out & p
    return out


def extract_equi_join_keys(condition: Expr) -> Optional[List[Tuple[str, str]]]:
    """If ``condition`` is a conjunction of column=column equalities, return
    the (left, right) column-name pairs; else None.

    Parity: JoinIndexRule's isJoinConditionSupported (reference
    rules/JoinIndexRule.scala:135) — only CNF of EqualTo over direct column
    refs is supported.
    """
    if condition is None:  # cross join: no keys
        return None
    pairs: List[Tuple[str, str]] = []
    for pred in split_conjunctive_predicates(condition):
        if isinstance(pred, EqualTo) and isinstance(pred.left, Col) \
                and isinstance(pred.right, Col):
            pairs.append((pred.left.column, pred.right.column))
        else:
            return None
    return pairs
