"""Expression tree for the logical plan IR.

The reference delegates expressions to Spark Catalyst; this is our minimal,
columnar, XLA-friendly equivalent. Every expression evaluates to a whole
column (vectorized) — there is no row-at-a-time path, matching how XLA wants
the work batched.

Supported surface (driven by the reference's rule requirements + TPC-H):
column refs, literals, comparisons, boolean algebra, IN-lists, arithmetic,
and aggregate functions (Sum/Count/Min/Max/Avg).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

from ..exceptions import HyperspaceException


class Expr:
    """Base class. Operators build trees; `references` lists column names."""

    def __eq__(self, other):  # == builds an expression, not a bool.
        return EqualTo(self, _wrap(other))

    def __ne__(self, other):
        return Not(EqualTo(self, _wrap(other)))

    def __lt__(self, other):
        return LessThan(self, _wrap(other))

    def __le__(self, other):
        return LessThanOrEqual(self, _wrap(other))

    def __gt__(self, other):
        return GreaterThan(self, _wrap(other))

    def __ge__(self, other):
        return GreaterThanOrEqual(self, _wrap(other))

    def __and__(self, other):
        return And(self, _wrap(other))

    def __or__(self, other):
        return Or(self, _wrap(other))

    def __invert__(self):
        return Not(self)

    def __add__(self, other):
        return Add(self, _wrap(other))

    def __radd__(self, other):
        return Add(_wrap(other), self)

    def __sub__(self, other):
        return Subtract(self, _wrap(other))

    def __rsub__(self, other):
        return Subtract(_wrap(other), self)

    def __mul__(self, other):
        return Multiply(self, _wrap(other))

    def __rmul__(self, other):
        return Multiply(_wrap(other), self)

    def __truediv__(self, other):
        return Divide(self, _wrap(other))

    def __hash__(self):
        return hash(repr(self))

    def isin(self, values: Sequence[Any]):
        return In(self, [_wrap(v) for v in values])

    def between(self, low, high):
        return And(GreaterThanOrEqual(self, _wrap(low)),
                   LessThanOrEqual(self, _wrap(high)))

    def alias(self, name: str):
        return Alias(self, name)

    @property
    def references(self) -> List[str]:
        out: List[str] = []
        for c in self.children:
            out.extend(c.references)
        # De-dup preserving order.
        seen = set()
        return [x for x in out if not (x in seen or seen.add(x))]

    @property
    def children(self) -> List["Expr"]:
        return []

    @property
    def name(self) -> str:
        """Output column name when projected."""
        return repr(self)


def _wrap(v) -> "Expr":
    if isinstance(v, Expr):
        return v
    return Lit(v)


@dataclass(frozen=True, eq=False, repr=False)
class Col(Expr):
    column: str

    @property
    def references(self) -> List[str]:
        return [self.column]

    @property
    def name(self) -> str:
        return self.column

    def __repr__(self):
        return f"col({self.column})"


@dataclass(frozen=True, eq=False, repr=False)
class Lit(Expr):
    value: Any

    def __post_init__(self):
        v = self.value
        if isinstance(v, datetime.date) and not isinstance(v, datetime.datetime):
            object.__setattr__(self, "value", v)
        elif not isinstance(v, (int, float, bool, str, type(None))):
            raise HyperspaceException(f"Unsupported literal type: {type(v)}")

    def __repr__(self):
        return f"lit({self.value!r})"


class _Binary(Expr):
    op_name = "?"
    symbol = "?"

    def __init__(self, left: Expr, right: Expr):
        self.left = left
        self.right = right

    @property
    def children(self) -> List[Expr]:
        return [self.left, self.right]

    def __repr__(self):
        return f"({self.left!r} {self.symbol} {self.right!r})"


class EqualTo(_Binary):
    op_name, symbol = "EqualTo", "="


class LessThan(_Binary):
    op_name, symbol = "LessThan", "<"


class LessThanOrEqual(_Binary):
    op_name, symbol = "LessThanOrEqual", "<="


class GreaterThan(_Binary):
    op_name, symbol = "GreaterThan", ">"


class GreaterThanOrEqual(_Binary):
    op_name, symbol = "GreaterThanOrEqual", ">="


class And(_Binary):
    op_name, symbol = "And", "AND"


class Or(_Binary):
    op_name, symbol = "Or", "OR"


class Add(_Binary):
    op_name, symbol = "Add", "+"


class Subtract(_Binary):
    op_name, symbol = "Subtract", "-"


class Multiply(_Binary):
    op_name, symbol = "Multiply", "*"


class Divide(_Binary):
    op_name, symbol = "Divide", "/"


class Not(Expr):
    op_name = "Not"

    def __init__(self, child: Expr):
        self.child = child

    @property
    def children(self) -> List[Expr]:
        return [self.child]

    def __repr__(self):
        return f"NOT({self.child!r})"


class In(Expr):
    op_name = "In"

    def __init__(self, value: Expr, options: List[Expr]):
        self.value = value
        self.options = options

    @property
    def children(self) -> List[Expr]:
        return [self.value] + list(self.options)

    def __repr__(self):
        return f"{self.value!r} IN ({', '.join(map(repr, self.options))})"


@dataclass(frozen=True, eq=False, repr=False)
class Alias(Expr):
    child: Expr
    alias_name: str

    @property
    def children(self) -> List[Expr]:
        return [self.child]

    @property
    def name(self) -> str:
        return self.alias_name

    def __repr__(self):
        return f"{self.child!r} AS {self.alias_name}"


# ---------------------------------------------------------------------------
# Aggregates.
# ---------------------------------------------------------------------------

class AggExpr(Expr):
    agg_name = "?"

    def __init__(self, child: Optional[Expr]):
        self.child = child

    @property
    def children(self) -> List[Expr]:
        return [self.child] if self.child is not None else []

    @property
    def name(self) -> str:
        inner = self.child.name if self.child is not None else "*"
        return f"{self.agg_name.lower()}({inner})"

    def __repr__(self):
        return self.name


class Sum(AggExpr):
    agg_name = "Sum"


class Count(AggExpr):
    agg_name = "Count"


class Min(AggExpr):
    agg_name = "Min"


class Max(AggExpr):
    agg_name = "Max"


class Avg(AggExpr):
    agg_name = "Avg"


class CountDistinct(AggExpr):
    """COUNT(DISTINCT child). Deliberately NOT a Count subclass: distinct
    counts are not decomposable (run partials cannot combine), so the
    two-phase and SPMD paths must not treat it as a plain count."""

    agg_name = "CountDistinct"

    def __init__(self, child: Expr):
        if child is None:
            raise ValueError("count_distinct requires a column expression")
        super().__init__(child)


# Public helpers (the pyspark-like functions module).

def col(name: str) -> Col:
    return Col(name)


def lit(value) -> Lit:
    return Lit(value)


def sum_(e) -> Sum:
    return Sum(_wrap(e) if not isinstance(e, Expr) else e)


def count(e=None) -> Count:
    return Count(_wrap(e) if e is not None and not isinstance(e, Expr) else e)


def min_(e) -> Min:
    return Min(_wrap(e) if not isinstance(e, Expr) else e)


def max_(e) -> Max:
    return Max(_wrap(e) if not isinstance(e, Expr) else e)


def avg(e) -> Avg:
    return Avg(_wrap(e) if not isinstance(e, Expr) else e)


def count_distinct(e) -> CountDistinct:
    if e is None:
        # count(None) means COUNT(*); the distinct analogue has no meaning.
        raise ValueError("count_distinct requires a column expression")
    return CountDistinct(_wrap(e) if not isinstance(e, Expr) else e)


# ---------------------------------------------------------------------------
# Predicate utilities used by the rewrite rules.
# ---------------------------------------------------------------------------

def rename_columns(e: Expr, rename) -> Expr:
    """Rebuild ``e`` with every Col reference passed through ``rename``
    (a str -> str mapping). Used by the DataFrame API's case-insensitive
    resolution: user-spelled names are rewritten to the schema's spelling
    before plan construction. Nodes without Col descendants are returned
    as-is (Exprs are immutable, sharing is safe)."""
    if isinstance(e, Col):
        new = rename(e.column)
        return e if new == e.column else Col(new)
    if isinstance(e, Lit):
        return e
    if isinstance(e, _Binary):
        return type(e)(rename_columns(e.left, rename),
                       rename_columns(e.right, rename))
    if isinstance(e, Not):
        return Not(rename_columns(e.child, rename))
    if isinstance(e, In):
        return In(rename_columns(e.value, rename),
                  [rename_columns(o, rename) for o in e.options])
    if isinstance(e, Alias):
        return Alias(rename_columns(e.child, rename), e.alias_name)
    if isinstance(e, AggExpr):
        if e.child is None:
            return e
        return type(e)(rename_columns(e.child, rename))
    raise HyperspaceException(f"Cannot rewrite expression {e!r}")


def split_conjunctive_predicates(e: Expr) -> List[Expr]:
    """Flatten nested ANDs into a list (CNF top level)."""
    if isinstance(e, And):
        return split_conjunctive_predicates(e.left) + split_conjunctive_predicates(e.right)
    return [e]


def extract_equi_join_keys(condition: Expr) -> Optional[List[Tuple[str, str]]]:
    """If ``condition`` is a conjunction of column=column equalities, return
    the (left, right) column-name pairs; else None.

    Parity: JoinIndexRule's isJoinConditionSupported (reference
    rules/JoinIndexRule.scala:135) — only CNF of EqualTo over direct column
    refs is supported.
    """
    pairs: List[Tuple[str, str]] = []
    for pred in split_conjunctive_predicates(condition):
        if isinstance(pred, EqualTo) and isinstance(pred.left, Col) \
                and isinstance(pred.right, Col):
            pairs.append((pred.left.column, pred.right.column))
        else:
            return None
    return pairs
