"""Logical plan IR.

The reference piggybacks on Spark Catalyst's LogicalPlan; this is our small,
columnar equivalent. Nodes carry resolved schemas (analysis happens at
construction). The rewrite rules (rules/) pattern-match these nodes exactly
the way the reference matches Scan→Filter(→Project) and Join subtrees.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ..exceptions import HyperspaceException
from ..schema import BOOL, DATE, FLOAT64, INT64, STRING, Field, Schema
from . import expr as E


def infer_dtype(e: E.Expr, schema: Schema) -> str:
    if isinstance(e, E.Col):
        return schema.field(e.column).dtype
    if isinstance(e, E.Alias):
        return infer_dtype(e.child, schema)
    if isinstance(e, E.Lit):
        v = e.value
        if isinstance(v, bool):
            return BOOL
        if isinstance(v, int):
            return INT64
        if isinstance(v, float):
            return FLOAT64
        if isinstance(v, datetime.date):
            return DATE
        if isinstance(v, str):
            return STRING
        raise HyperspaceException(f"Cannot infer type of literal {v!r}")
    if isinstance(e, (E.EqualTo, E.LessThan, E.LessThanOrEqual, E.GreaterThan,
                      E.GreaterThanOrEqual, E.And, E.Or, E.Not, E.In,
                      E.Like, E.IsNull)):
        return BOOL
    if isinstance(e, E.DatePart):
        return INT64
    if isinstance(e, (E.Substring, E.StringTransform)):
        if infer_dtype(e.child, schema) != STRING:
            raise HyperspaceException(
                f"{e.op_name} requires a string operand: {e!r}")
        return STRING
    if isinstance(e, E.CaseWhen):
        values = [v for _, v in e.branches]
        if e.else_value is not None:
            values.append(e.else_value)
        # Explicit NULL branches contribute nullability, not a type.
        kinds = [infer_dtype(v, schema) for v in values
                 if not (isinstance(v, E.Lit) and v.value is None)]
        if not kinds:
            raise HyperspaceException(
                f"CASE with only NULL branches has no type: {e!r}")
        uniq = set(kinds)
        if len(uniq) == 1:
            return kinds[0]
        numeric = {INT64, "int32", FLOAT64, "float32"}
        if uniq <= numeric:
            return FLOAT64 if (FLOAT64 in uniq or "float32" in uniq) else INT64
        raise HyperspaceException(
            f"CASE branches have incompatible types {sorted(uniq)}: {e!r}")
    if isinstance(e, (E.Add, E.Subtract, E.Multiply)):
        kinds = {infer_dtype(c, schema) for c in e.children}
        return FLOAT64 if (FLOAT64 in kinds or "float32" in kinds) else INT64
    if isinstance(e, (E.Divide, E.Sqrt)):
        return FLOAT64
    if isinstance(e, E.NullLit):
        return e.dtype
    if isinstance(e, E.Concat):
        for p in e.parts:
            pt = infer_dtype(p, schema)
            if pt != STRING:
                raise HyperspaceException(
                    f"concat() operands must be strings; got {pt}")
        return STRING
    if isinstance(e, (E.Count, E.CountDistinct)):
        return INT64
    if isinstance(e, E.Avg):
        return FLOAT64
    if isinstance(e, E.Sum):
        child = infer_dtype(e.child, schema)
        return FLOAT64 if child in (FLOAT64, "float32") else INT64
    if isinstance(e, (E.Min, E.Max)):
        return infer_dtype(e.child, schema)
    if isinstance(e, E.WindowExpr):
        if e.fn in E.WindowExpr.RANK_FNS or e.fn == "count":
            return INT64
        if e.fn == "avg":
            return FLOAT64
        child = infer_dtype(e.arg, schema)
        if e.fn == "sum":
            return FLOAT64 if child in (FLOAT64, "float32") else INT64
        return child  # min/max
    raise HyperspaceException(f"Cannot infer type of {e!r}")


class LogicalPlan:
    """Base node. ``schema`` is the resolved output schema."""

    @property
    def children(self) -> List["LogicalPlan"]:
        return []

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def node_name(self) -> str:
        return type(self).__name__

    def collect_leaves(self) -> List["LogicalPlan"]:
        if not self.children:
            return [self]
        out = []
        for c in self.children:
            out.extend(c.collect_leaves())
        return out

    def transform_up(self, fn) -> "LogicalPlan":
        new_children = [c.transform_up(fn) for c in self.children]
        node = self.with_children(new_children) if new_children != self.children else self
        return fn(node)

    def with_children(self, children: List["LogicalPlan"]) -> "LogicalPlan":
        if children:
            raise HyperspaceException(f"{self.node_name} has no children to replace")
        return self

    def simple_string(self) -> str:
        return self.node_name

    def tree_string(self, depth: int = 0) -> str:
        lines = ["  " * depth + ("+- " if depth else "") + self.simple_string()]
        for c in self.children:
            lines.append(c.tree_string(depth + 1))
        return "\n".join(lines)

    # Plan-node names feed the PlanSignatureProvider fingerprint.
    def node_names_preorder(self) -> List[str]:
        out = [self.node_name]
        for c in self.children:
            out.extend(c.node_names_preorder())
        return out


class Scan(LogicalPlan):
    """Leaf: scan a file-based relation (LogicalRelation analogue).

    ``skipping_note``: set by DataSkippingIndexRule when it narrows the
    relation's file list, so golden plans and explain render the pruning
    (e.g. "[1/4 files after skipping]")."""

    def __init__(self, relation, skipping_note: Optional[str] = None):
        self.relation = relation  # sources.FileBasedRelation
        self.skipping_note = skipping_note

    @property
    def schema(self) -> Schema:
        return self.relation.schema

    def simple_string(self) -> str:
        return f"Scan {self.relation.describe()}" + \
            (f" [{self.skipping_note}]" if self.skipping_note else "")


class IndexScan(LogicalPlan):
    """Leaf: scan the bucketed files of a covering index version.

    This is the analogue of the reference's IndexHadoopFsRelation swap
    (rules/RuleUtils.scala:253): instead of the source files, read the
    index's own parquet files, optionally exposing the bucket spec so joins
    can go shuffle-free and filters can prune buckets.
    """

    def __init__(self, index_entry, schema: Schema, use_bucket_spec: bool = False,
                 deleted_file_ids: Optional[Sequence[int]] = None,
                 appended_files: Optional[Sequence[str]] = None):
        self.index_entry = index_entry
        self._schema = schema
        self.use_bucket_spec = use_bucket_spec
        # Hybrid Scan state: rows from these source-file ids must be masked
        # out (deleted) and these source files merged in (appended).
        self.deleted_file_ids = list(deleted_file_ids or [])
        self.appended_files = list(appended_files or [])

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        e = self.index_entry
        extra = ""
        if self.deleted_file_ids or self.appended_files:
            extra = (f", hybrid(+{len(self.appended_files)} appended,"
                     f" -{len(self.deleted_file_ids)} deleted files)")
        return (f"IndexScan Hyperspace(Type: {e.derivedDataset.kind_abbr}, "
                f"Name: {e.name}, LogVersion: {e.log_version}{extra})")


class Filter(LogicalPlan):
    def __init__(self, condition: E.Expr, child: LogicalPlan):
        for ref in condition.references:
            if ref not in child.schema:
                raise HyperspaceException(
                    f"Filter references unknown column '{ref}'; "
                    f"available: {child.schema.names}")
        self.condition = condition
        self.child = child

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    def with_children(self, children):
        return Filter(self.condition, children[0])

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def simple_string(self) -> str:
        return f"Filter ({self.condition!r})"


class Project(LogicalPlan):
    def __init__(self, exprs: Sequence[E.Expr], child: LogicalPlan):
        self.exprs = [E.Col(e) if isinstance(e, str) else e for e in exprs]
        for e in self.exprs:
            for ref in e.references:
                if ref not in child.schema:
                    raise HyperspaceException(
                        f"Project references unknown column '{ref}'; "
                        f"available: {child.schema.names}")
        self.child = child
        names = [e.name for e in self.exprs]
        if len(set(names)) != len(names):
            raise HyperspaceException(f"Duplicate output columns in project: {names}")
        self._schema = Schema(
            [Field(e.name, infer_dtype(e, child.schema)) for e in self.exprs])

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    def with_children(self, children):
        return Project(self.exprs, children[0])

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        return f"Project [{', '.join(e.name for e in self.exprs)}]"


class Join(LogicalPlan):
    """``reorder_note``: set by the cost-based join reorderer
    (optimizer/join_order.py) on joins it re-linearized, so explain and
    golden plans render the rewrite (e.g. "[reordered, est~120 rows]") —
    the same convention as Scan.skipping_note."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan, condition: E.Expr,
                 join_type: str = "inner",
                 reorder_note: Optional[str] = None):
        if join_type not in ("inner", "left", "right", "full", "semi",
                             "anti", "cross"):
            raise HyperspaceException(f"Unsupported join type: {join_type}")
        if join_type == "cross":
            if condition is not None:
                raise HyperspaceException("Cross join takes no condition")
        elif condition is None:
            raise HyperspaceException(
                f"{join_type} join requires a condition")
        overlap = set(left.schema.names) & set(right.schema.names)
        if overlap:
            raise HyperspaceException(
                f"Ambiguous join output columns {sorted(overlap)}; "
                "rename before joining")
        # Validate references resolve against the combined schema.
        combined = list(left.schema.fields) + list(right.schema.fields)
        names = {f.name for f in combined}
        for ref in (condition.references if condition is not None else ()):
            if ref not in names:
                raise HyperspaceException(f"Join condition references unknown '{ref}'")
        self.left = left
        self.right = right
        self.condition = condition
        self.join_type = join_type
        self.reorder_note = reorder_note
        if join_type in ("semi", "anti"):
            # Semi/anti joins emit only the left side's rows (the right
            # side is an existence probe) — the lowering target for SQL
            # [NOT] IN / [NOT] EXISTS subqueries.
            self._schema = left.schema
            return
        # Outer joins null-fill the non-preserved side's columns.
        if join_type in ("left", "right", "full"):
            from ..schema import Field
            left_nullable = join_type in ("right", "full")
            right_nullable = join_type in ("left", "full")
            combined = [
                Field(f.name, f.dtype,
                      f.nullable or (left_nullable if f.name in
                                     left.schema else right_nullable))
                for f in combined]
        self._schema = Schema(combined)

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.left, self.right]

    def with_children(self, children):
        return Join(children[0], children[1], self.condition, self.join_type,
                    self.reorder_note)

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        note = f" [{self.reorder_note}]" if self.reorder_note else ""
        if self.join_type == "cross":
            return "Join cross" + note
        return f"Join {self.join_type} ({self.condition!r})" + note


class Aggregate(LogicalPlan):
    def __init__(self, group_cols: Sequence[str], aggs: Sequence[E.Expr],
                 child: LogicalPlan):
        self.group_cols = list(group_cols)
        self.aggs = list(aggs)
        for g in self.group_cols:
            if g not in child.schema:
                raise HyperspaceException(f"Group column '{g}' not in {child.schema.names}")
        self.child = child
        fields = [child.schema.field(g) for g in self.group_cols]
        for a in self.aggs:
            fields.append(Field(a.name, infer_dtype(a, child.schema)))
        self._schema = Schema(fields)

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    def with_children(self, children):
        return Aggregate(self.group_cols, self.aggs, children[0])

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        return (f"Aggregate [{', '.join(self.group_cols)}] "
                f"[{', '.join(a.name for a in self.aggs)}]")


class Window(LogicalPlan):
    """Analytic functions over partitions: appends one output column per
    WindowExpr to the child's schema, preserving the child's row order
    (values are computed in partition-sorted space and scattered back).

    The reference inherits window execution from Spark SQL
    (window exprs appear throughout its TPC-DS golden corpus, e.g.
    src/test/resources/tpcds/queries/q51.sql, q63.sql, q89.sql); here it
    is a first-class plan node executed as sort + segmented scans on
    device. Window argument/partition/order expressions must be plain
    columns — the SQL front-end materializes anything else first."""

    def __init__(self, wexprs: Sequence[Tuple[str, E.WindowExpr]],
                 child: LogicalPlan):
        if not wexprs:
            raise HyperspaceException("Window requires at least one expr")
        self.wexprs = [(name, w) for name, w in wexprs]
        for name, w in self.wexprs:
            for ref in w.references:
                if ref not in child.schema:
                    raise HyperspaceException(
                        f"Window expr references unknown column '{ref}'; "
                        f"available: {child.schema.names}")
            for p in w.partition:
                if not isinstance(p, E.Col):
                    raise HyperspaceException(
                        f"Window PARTITION BY must be plain columns; "
                        f"got {p!r}")
            for o, _ in w.orders:
                if not isinstance(o, E.Col):
                    raise HyperspaceException(
                        f"Window ORDER BY must be plain columns; got {o!r}")
            if w.arg is not None and not isinstance(w.arg, E.Col):
                raise HyperspaceException(
                    f"Window argument must be a plain column; got {w.arg!r}")
            if name in child.schema:
                raise HyperspaceException(
                    f"Window output '{name}' collides with input column")
        self.child = child
        fields = list(child.schema.fields)
        for name, w in self.wexprs:
            fields.append(Field(name, infer_dtype(w, child.schema)))
        self._schema = Schema(fields)

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    def with_children(self, children):
        return Window(self.wexprs, children[0])

    @property
    def schema(self) -> Schema:
        return self._schema

    def simple_string(self) -> str:
        return ("Window [" + ", ".join(
            f"{name}={w!r}" for name, w in self.wexprs) + "]")


class Sort(LogicalPlan):
    def __init__(self, orders: Sequence[Tuple[str, bool]], child: LogicalPlan):
        # orders: (column, ascending)
        self.orders = [(c, asc) for c, asc in orders]
        for c, _ in self.orders:
            if c not in child.schema:
                raise HyperspaceException(f"Sort column '{c}' not in {child.schema.names}")
        self.child = child

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    def with_children(self, children):
        return Sort(self.orders, children[0])

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def simple_string(self) -> str:
        parts = [f"{c} {'ASC' if a else 'DESC'}" for c, a in self.orders]
        return f"Sort [{', '.join(parts)}]"


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan):
        self.n = n
        self.child = child

    @property
    def children(self) -> List[LogicalPlan]:
        return [self.child]

    def with_children(self, children):
        return Limit(self.n, children[0])

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def simple_string(self) -> str:
        return f"Limit {self.n}"


class BucketUnion(LogicalPlan):
    """Partition-aligned union of bucketed outputs (reference:
    plans/logical/BucketUnion.scala:31). On TPU this is a pure concatenation
    of shard-aligned arrays — no collective needed (SURVEY §5)."""

    def __init__(self, children: List[LogicalPlan], bucket_spec):
        if not children:
            raise HyperspaceException("BucketUnion requires children")
        first = children[0].schema.names
        for c in children[1:]:
            if c.schema.names != first:
                raise HyperspaceException("BucketUnion children must share schema")
        self._children = children
        self.bucket_spec = bucket_spec

    @property
    def children(self) -> List[LogicalPlan]:
        return list(self._children)

    def with_children(self, children):
        return BucketUnion(children, self.bucket_spec)

    @property
    def schema(self) -> Schema:
        return self._children[0].schema


class Union(LogicalPlan):
    def __init__(self, children: List[LogicalPlan]):
        if not children:
            raise HyperspaceException("Union requires children")
        first = children[0].schema.names
        for c in children[1:]:
            if c.schema.names != first:
                raise HyperspaceException("Union children must share schema")
        self._children = children

    @property
    def children(self) -> List[LogicalPlan]:
        return list(self._children)

    def with_children(self, children):
        return Union(children)

    @property
    def schema(self) -> Schema:
        return self._children[0].schema


@dataclass(frozen=True)
class BucketSpec:
    """Bucketing metadata carried by index scans (Spark BucketSpec analogue)."""

    num_buckets: int
    bucket_column_names: Tuple[str, ...]
    sort_column_names: Tuple[str, ...]
