"""Session + DataFrame API.

The SparkSession analogue: holds the conf, the pluggable source-provider
manager, and the optimizer-rule batch that `enable_hyperspace()` injects
(parity: package.scala:35-75 — the reference splices JoinIndexRule ::
FilterIndexRule into experimentalMethods.extraOptimizations).

DataFrames are thin wrappers over the logical plan IR; `collect()` runs
analysis → (hyperspace rewrite if enabled) → the XLA executor.
"""

from __future__ import annotations

import importlib
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple, Union as TUnion

from .config import Conf, HyperspaceConf
from .exceptions import HyperspaceException
from .plan import expr as E
from .plan.nodes import (Aggregate, Filter, Join, Limit, LogicalPlan, Project,
                         Scan, Sort, Union, Window)
from .schema import Schema
from .sources.interfaces import FileBasedSourceProviderManager
from .telemetry import span_names as SN
from .telemetry import trace as _trace


class Session:
    def __init__(self, conf: Optional[Dict[str, str]] = None,
                 system_path: Optional[str] = None):
        # Backend-aware persistent-cache setup (no-op after the first
        # session; initializes the jax backend, which callers that switch
        # platforms in-process have already pinned by now).
        from .execution import ensure_compilation_cache
        ensure_compilation_cache()
        self.conf = Conf(conf)
        if system_path is not None:
            from .index.constants import IndexConstants
            self.conf.set(IndexConstants.INDEX_SYSTEM_PATH, system_path)
        self.hs_conf = HyperspaceConf(self.conf)
        self._hyperspace_enabled = False
        self._event_logger = None
        # whyNot reasons of the most recent hyperspace rewrite pass —
        # PER THREAD (threading.local behind the property below): on the
        # multi-threaded serving path, one thread's optimize must not
        # clobber the collector another thread's workload capture is
        # about to attribute from.
        self._reason_tls = threading.local()
        from .config import CacheWithTransform
        self._provider_manager_cache = CacheWithTransform(
            self.hs_conf.file_based_source_builders, self._build_provider_manager)
        self._index_collection_manager = None
        # Serving layer: the result cache instance follows the serving
        # conf string (enabled flag + budgets) — rebuilt, and thereby
        # cleared, when that changes. The SQL plan memo keys on the
        # temp-view registry version (any view change flips it).
        self._result_cache_holder = CacheWithTransform(
            self.hs_conf.result_cache_conf_string, self._build_result_cache)
        # CacheWithTransform carries its own lock (config.py), but the
        # holder's build function touches session state: keep the outer
        # lock for the multi-threaded serving path's execute() probes.
        self._result_cache_lock = threading.Lock()
        # Temp views: eager dict + lock — registrations can race with
        # serving-path sql() lowering reading the registry version.
        self._temp_views: Dict[str, LogicalPlan] = {}
        self._views_lock = threading.Lock()
        self._temp_views_version = 0
        # Advisor state: the in-session workload log (advisor/workload.py
        # — created eagerly: a lazy check-then-create would race between
        # serving threads and lose records) and per-index applied counts
        # (rule_utils.log_index_usage increments under the lock;
        # statistics surface them).
        from .advisor.workload import WorkloadLog
        self._workload_log = WorkloadLog()
        self._index_usage_counts: Dict[str, int] = {}
        self._usage_counts_lock = threading.Lock()
        self._sql_plan_cache: "OrderedDict[Tuple, LogicalPlan]" = OrderedDict()
        self._sql_plan_stats = {"hits": 0, "misses": 0}
        # Cost-based optimizer state (optimizer/): the lazily-created
        # statistics provider (optimizer/stats.py attaches it on first
        # use), the chain records of the most recent join-reorder pass
        # (explain's "Join order:" section + bench's q-error read them),
        # and the observed output rows of recently executed inner joins
        # (executor-recorded; keyed by the composite join_actual_key —
        # condition repr + both side signatures — LRU-bounded).
        self._stats_provider = None
        self._last_join_order: Optional[list] = None
        self._join_actuals: "OrderedDict[str, int]" = OrderedDict()
        # The actuals dict is written by the executor on the
        # multi-threaded serving path (like _usage_counts, it needs its
        # own lock: unlocked LRU eviction could evict a key another
        # thread is about to move_to_end).
        self._join_actuals_lock = threading.Lock()
        # The memo is on the multi-threaded serving path (like the
        # result cache, which carries its own lock).
        self._sql_plan_lock = threading.Lock()
        # Span-tree trace of the most recent traced execution
        # (telemetry/trace.py; None until telemetry.trace.enabled runs a
        # query). Read by Hyperspace.last_trace() and explain's
        # "Trace:" section.
        self._last_trace = None
        # Artifact boot preload (r20, opt-in): warm the compiled-program
        # caches from the lake's AOT store, usage-ordered, within the
        # preload.maxMs/maxBytes budgets — so THIS process reaches its
        # first query with compile count ~ 0. Strictly best-effort: a
        # session must come up even with an unreadable artifact dir.
        if self.hs_conf.artifacts_preload_enabled():
            try:
                from .artifacts.manager import preload as _artifact_preload
                _artifact_preload(self)
            except Exception:
                pass

    # The reason collector of the calling thread's most recent rewrite
    # pass. Plain attribute syntax everywhere (apply_hyperspace writes,
    # why_not/capture read); the thread-local backing is invisible.
    @property
    def _last_reason_collector(self):
        return getattr(self._reason_tls, "collector", None)

    @_last_reason_collector.setter
    def _last_reason_collector(self, ctx) -> None:
        self._reason_tls.collector = ctx

    @property
    def index_collection_manager(self):
        """The per-session caching index manager (HyperspaceContext parity:
        rules and the user facade share one instance + one cache)."""
        if self._index_collection_manager is None:
            from .index.manager import CachingIndexCollectionManager
            self._index_collection_manager = CachingIndexCollectionManager(self)
        return self._index_collection_manager

    def _build_result_cache(self, raw: str):
        from .serving.result_cache import build_result_cache
        return build_result_cache(self)

    @property
    def result_cache(self):
        """The serving-layer result cache (serving/result_cache.py), or
        None while ``serving.result_cache.enabled`` is false."""
        with self._result_cache_lock:
            return self._result_cache_holder.load()

    @property
    def read(self) -> "DataFrameReader":
        # Fresh reader per access so option() calls don't leak across reads.
        return DataFrameReader(self)

    # ------------------------------------------------------------------
    # Temp views (parity: the reference's E2E suites query indexed data
    # through Spark views; view names are case-insensitive like Spark's).
    # ------------------------------------------------------------------

    def create_temp_view(self, name: str, df: "DataFrame",
                         replace: bool = False) -> None:
        key = name.lower()
        with self._views_lock:
            if key in self._temp_views and not replace:
                raise HyperspaceException(
                    f"Temp view already exists: {name}")
            self._temp_views[key] = df.plan
            self._temp_views_version += 1

    def table(self, name: str) -> "DataFrame":
        """DataFrame over a registered temp view. The view shares the
        underlying plan, so index rewrites (signatures are plan+file
        based) apply exactly as they do on the original DataFrame."""
        key = name.lower()
        with self._views_lock:
            plan = self._temp_views.get(key)
        if plan is None:
            raise HyperspaceException(f"No such temp view: {name}")
        return DataFrame(self, plan)

    def drop_temp_view(self, name: str) -> bool:
        with self._views_lock:
            dropped = self._temp_views.pop(name.lower(), None) is not None
            if dropped:
                self._temp_views_version += 1
        return dropped

    # ------------------------------------------------------------------
    # Source providers (parity: FileBasedSourceProviderManager.buildProviders).
    # ------------------------------------------------------------------

    @property
    def source_provider_manager(self) -> FileBasedSourceProviderManager:
        # Re-derived only when the conf string changes (CacheWithTransform).
        return self._provider_manager_cache.load()

    @staticmethod
    def _build_provider_manager(raw: str) -> FileBasedSourceProviderManager:
        providers = []
        for name in raw.split(","):
            name = name.strip()
            module_name, _, cls_name = name.rpartition(".")
            try:
                cls = getattr(importlib.import_module(module_name), cls_name)
            except (ImportError, AttributeError) as e:
                raise HyperspaceException(f"Cannot load source builder {name}") from e
            providers.append(cls())
        return FileBasedSourceProviderManager(providers)

    # ------------------------------------------------------------------
    # Hyperspace enable/disable (parity: package.scala:35-75).
    # ------------------------------------------------------------------

    def enable_hyperspace(self) -> "Session":
        self._hyperspace_enabled = True
        return self

    def disable_hyperspace(self) -> "Session":
        self._hyperspace_enabled = False
        return self

    def is_hyperspace_enabled(self) -> bool:
        return self._hyperspace_enabled

    # ------------------------------------------------------------------
    # Execution.
    # ------------------------------------------------------------------

    def optimize(self, plan: LogicalPlan,
                 _pre_normalized: bool = False,
                 diagnostic: bool = False) -> LogicalPlan:
        """General optimizations (column pruning), the hyperspace rewrite
        batch if enabled, then partition pruning. Partition pruning is
        always on (like Spark's native pruning) but must run AFTER the
        index rules: it narrows a Scan's file list, and the index rules
        fingerprint the relation's full file listing — pruning first would
        mismatch every index signature (same ordering rule as the
        data-skipping rule inside the batch).

        ``_pre_normalized``: the caller already ran serving.fingerprint.
        normalize (= the first two passes here) on ``plan`` — skip them
        rather than re-walking the tree (the result-cache miss path).

        ``diagnostic``: an inspection pass (explain) that will not
        execute the result — the rewrite runs with a silent collector,
        so it emits no usage telemetry, bumps no usageCount, and leaves
        the last real pass's whyNot reasons in place."""
        from .rules.column_pruning import prune_columns
        from .rules.pushdown import push_filters
        from .sources.partitions import prune_partitions
        # Catalyst-parity normalization first: predicates sink below
        # projections so the index rules see Scan→Filter shapes regardless
        # of how the user ordered select()/where().
        if not _pre_normalized:
            with _trace.span(SN.PLAN_NORMALIZE):
                plan = push_filters(plan)
                plan = prune_columns(plan)
        # Cost-based join reordering (optimizer/join_order.py) runs AFTER
        # normalization (it wants the pushed-down filters for selectivity)
        # and BEFORE the index rules, so FilterIndexRule/JoinIndexRule and
        # the advisor's what-if hooks match the reordered tree unchanged.
        # It is NOT part of serving.fingerprint.normalize: the result-cache
        # key's conf hash pins the reorder flag instead.
        if self.hs_conf.join_reorder_enabled():
            from .optimizer.join_order import reorder_joins
            plan = reorder_joins(self, plan, diagnostic=diagnostic)
        if self._hyperspace_enabled:
            from .rules.apply_hyperspace import apply_hyperspace
            ctx = None
            if diagnostic:
                from .rules.index_filters import ReasonCollector
                ctx = ReasonCollector(
                    self.hs_conf.filter_reason_enabled(), silent=True)
            plan = apply_hyperspace(self, plan, ctx)
        return prune_partitions(plan)

    def execute(self, plan: LogicalPlan, context=None):
        """Execute a plan under an explicit :class:`QueryContext`
        (serving/context.py). The context pins the per-query state that
        used to be implicit session attributes — result-cache handle,
        capture decision, io attribution — so the serving frontend can
        thread many concurrent queries (possibly sharing a process-wide
        cache) through shared worker threads. Callers that pass no
        context get a session-scoped one per call."""
        from .robustness import faults as _faults
        from .serving.context import QueryContext
        ctx = context if context is not None \
            else QueryContext.for_session(self)
        # The trace root (telemetry/trace.py): a no-op unless
        # telemetry.trace.enabled is set on this session or the serving
        # frontend handed the context a shared sweep trace; the opt-in
        # jax.profiler hook brackets the first query after arming. The
        # fault scope (robustness/faults.py) arms this session's
        # robustness.faults.* conf for exactly this execution — skipped
        # entirely (no contextvar write) while nothing is armed.
        with ctx.activate(), _faults.scope_for(self.hs_conf), \
                _trace.maybe_profile(self), _trace.query_trace(self, ctx):
            t0 = time.perf_counter()
            error = False
            suppress = False
            try:
                if not ctx.capture:
                    return self._execute_uncaptured(plan, ctx)
                # Advisor workload capture (advisor/workload.py): time
                # whatever path actually runs and record the canonical
                # plan + shapes + applied indexes. Resetting the reason
                # collector first makes ``applied`` attributable to THIS
                # execution (a result-cache hit runs no rewrite pass and
                # records an empty applied set).
                self._last_reason_collector = None
                table = self._execute_uncaptured(plan, ctx)
                from .advisor.workload import capture_execution
                capture_execution(self, plan, time.perf_counter() - t0)
                return table
            except BaseException as exc:
                error = True
                # A failed query is tail-keep-worthy by definition —
                # and this is where worker-thread failures (whose emit
                # sites never see the query's contextvars) surface on
                # the query's own context.
                _trace.keep_active("error")
                # A sweep-member failure the frontend's ladder will
                # rescue must not count as a completed failed query
                # (the standalone rerun records the real outcome);
                # deadline cancellations skip the rerun, so they stay.
                from .exceptions import QueryDeadlineError
                suppress = ctx.slo_suppress_error and \
                    not isinstance(exc, QueryDeadlineError)
                raise
            finally:
                # SLO sensor feed (telemetry/slo.py): every query's
                # (latency, error, degraded) lands in the sliding window
                # + the live query-latency histogram — inside the trace
                # scope, so a breach event correlates with its query.
                if not suppress:
                    from .telemetry import slo as _slo
                    _slo.observe_query(
                        self, (time.perf_counter() - t0) * 1000.0,
                        error=error, degraded=ctx.degraded)

    def _execute_uncaptured(self, plan: LogicalPlan, ctx=None):
        if not self.hs_conf.adaptive_replan_enabled():
            return self._execute_once(plan, ctx)
        # Mid-query re-planning (adaptive/feedback.py): the staged
        # executor raises ReplanRequested at a join stage boundary whose
        # observed actual blew past its estimate. The observation
        # already landed in the correction store, so the re-optimize
        # pass below plans with the measured cardinality; the suppress
        # guard makes the retry run to completion (one re-plan per
        # query).
        from .adaptive import feedback as _feedback
        try:
            return self._execute_once(plan, ctx)
        except _feedback.ReplanRequested as rr:
            _feedback.emit_replan_event(self, rr)
            with _feedback.suppress_replans():
                return self._execute_once(plan, ctx)

    def _execute_once(self, plan: LogicalPlan, ctx=None):
        cache = ctx.result_cache if ctx is not None else self.result_cache
        if cache is not None:
            # Serving path: probe the result cache first — a hit skips
            # the rewrite batch AND execution (serving/result_cache.py);
            # a miss executes below and runs the admission policy. With
            # a frontend-owned context the cache may be the process-wide
            # CROSS-SESSION one — its keys pin plan, sources, index log
            # versions, and this session's conf hash, so sharing is safe
            # by construction.
            from .serving.result_cache import execute_with_cache
            return execute_with_cache(self, cache, plan)
        return self._run_optimized(self.optimize(plan))

    def _run_optimized(self, optimized: LogicalPlan):
        from .execution import execute as run
        trace_dir = self.hs_conf.trace_dir()
        if trace_dir:
            # XLA-profiler integration (SURVEY §5): device timelines for
            # every jitted program this execution launches, viewable in
            # TensorBoard / xprof.
            import jax

            with jax.profiler.trace(trace_dir):
                return run(optimized, session=self)
        return run(optimized, session=self)

    def create_dataframe(self, plan: LogicalPlan) -> "DataFrame":
        return DataFrame(self, plan)

    def sql(self, text: str) -> "DataFrame":
        """Lower one SQL SELECT over registered temp views onto the
        DataFrame IR (see hyperspace_tpu/sql.py for the supported
        subset); index rewrites apply exactly as for DataFrame queries.

        With the serving result cache enabled, the lowered plan is also
        memoized per (text, temp-view registry version, case mode) — the
        parse+analyze pass is pure given those, and a serving workload
        re-issues identical texts."""
        from .sql import sql as _sql
        size = self.hs_conf.result_cache_plan_cache_size() \
            if self.result_cache is not None else 0
        if size <= 0:
            return _sql(self, text)
        key = (text, self._temp_views_version,
               self.hs_conf.case_sensitive())
        with self._sql_plan_lock:
            plan = self._sql_plan_cache.get(key)
            if plan is not None:
                self._sql_plan_cache.move_to_end(key)
                self._sql_plan_stats["hits"] += 1
                return DataFrame(self, plan)
            self._sql_plan_stats["misses"] += 1
        df = _sql(self, text)
        with self._sql_plan_lock:
            self._sql_plan_cache[key] = df.plan
            while len(self._sql_plan_cache) > size:
                self._sql_plan_cache.popitem(last=False)
        return df


class DataFrameReader:
    def __init__(self, session: Session):
        self._session = session
        self._options: Dict[str, str] = {}

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key] = str(value)
        return self

    def parquet(self, *paths: str) -> "DataFrame":
        return self.format("parquet").load(*paths)

    def csv(self, *paths: str) -> "DataFrame":
        return self.format("csv").load(*paths)

    def json(self, *paths: str) -> "DataFrame":
        """Newline-delimited JSON files."""
        return self.format("json").load(*paths)

    def orc(self, *paths: str) -> "DataFrame":
        return self.format("orc").load(*paths)

    def text(self, *paths: str) -> "DataFrame":
        """One string column "value" per line (Spark text source)."""
        return self.format("text").load(*paths)

    def avro(self, *paths: str) -> "DataFrame":
        """Avro object container files (built-in reader, util/avro.py)."""
        return self.format("avro").load(*paths)

    def delta(self, path: str, version_as_of: Optional[int] = None
              ) -> "DataFrame":
        """Read a commit-log versioned table (lake/delta.py), optionally
        time-traveling to an older version."""
        reader = self.format("delta")
        if version_as_of is not None:
            reader._options["versionAsOf"] = str(version_as_of)
        return reader.load(path)

    def iceberg(self, path: str, snapshot_id: Optional[int] = None
                ) -> "DataFrame":
        """Read a snapshot/manifest versioned table (lake/iceberg.py)."""
        reader = self.format("iceberg")
        if snapshot_id is not None:
            reader._options["snapshotId"] = str(snapshot_id)
        return reader.load(path)

    def format(self, fmt: str) -> "_FormattedReader":
        return _FormattedReader(self._session, fmt, dict(self._options))


class _FormattedReader:
    def __init__(self, session: Session, fmt: str, options: Dict[str, str]):
        self._session = session
        self._fmt = fmt
        self._options = options

    def load(self, *paths: str) -> "DataFrame":
        relation = self._session.source_provider_manager.build_relation(
            list(paths), self._fmt, self._options)
        return DataFrame(self._session, Scan(relation))


class DataFrame:
    def __init__(self, session: Session, plan: LogicalPlan):
        self.session = session
        self.plan = plan

    # ------------------------------------------------------------------
    # Transformations.
    # ------------------------------------------------------------------

    @property
    def schema(self) -> Schema:
        return self.plan.schema

    @property
    def columns(self) -> List[str]:
        return self.plan.schema.names

    # Column names resolve case-insensitively against the schema (Spark
    # analyzer behavior; `hyperspace.caseSensitive=true` restores exact
    # matching). Unresolvable names pass through unchanged so the plan
    # constructors raise with the user's spelling.
    def _spelling(self, name: str, names: Optional[List[str]] = None) -> str:
        from .util.resolver import resolve
        avail = names if names is not None else self.plan.schema.names
        r = resolve(avail, name, self.session.hs_conf.case_sensitive())
        return r if r is not None else name

    def _resolve_expr(self, e: E.Expr,
                      names: Optional[List[str]] = None) -> E.Expr:
        return E.rename_columns(e, lambda n: self._spelling(n, names))

    def filter(self, condition: E.Expr) -> "DataFrame":
        return DataFrame(self.session,
                         Filter(self._resolve_expr(condition), self.plan))

    where = filter

    def select(self, *exprs: TUnion[str, E.Expr]) -> "DataFrame":
        flat: List[TUnion[str, E.Expr]] = []
        for e in exprs:
            if isinstance(e, (list, tuple)):
                flat.extend(e)
            else:
                flat.append(e)
        flat = [self._spelling(e) if isinstance(e, str)
                else self._resolve_expr(e) for e in flat]
        return DataFrame(self.session, Project(flat, self.plan))

    def join(self, other: "DataFrame", on: E.Expr, how: str = "inner") -> "DataFrame":
        both = list(self.plan.schema.names) + list(other.plan.schema.names)
        return DataFrame(self.session,
                         Join(self.plan, other.plan,
                              self._resolve_expr(on, both), how))

    def cross_join(self, other: "DataFrame") -> "DataFrame":
        """Cartesian product (Spark's crossJoin). The SQL front-end emits
        this only for single-row sides (comma-joined global aggregates —
        the TPC-DS q28/q61/q88/q90 shape)."""
        return DataFrame(self.session,
                         Join(self.plan, other.plan, None, "cross"))

    crossJoin = cross_join

    def group_by(self, *cols: str) -> "GroupedData":
        return GroupedData(self, [self._spelling(c) for c in cols])

    groupBy = group_by

    def agg(self, *aggs: E.Expr) -> "DataFrame":
        return DataFrame(self.session,
                         Aggregate([], [self._resolve_expr(a) for a in aggs],
                                   self.plan))

    def sort(self, *orders) -> "DataFrame":
        normalized: List[Tuple[str, bool]] = []
        for o in orders:
            if isinstance(o, str):
                normalized.append((self._spelling(o), True))
            else:
                name, asc = o
                normalized.append((self._spelling(name), asc))
        return DataFrame(self.session, Sort(normalized, self.plan))

    order_by = sort
    orderBy = sort

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self.session, Limit(n, self.plan))

    # ------------------------------------------------------------------
    # Actions.
    # ------------------------------------------------------------------

    def execute(self):
        """Run the (possibly rewritten) plan; returns the device Table."""
        return self.session.execute(self.plan)

    def optimized_plan(self) -> LogicalPlan:
        return self.session.optimize(self.plan)

    def to_arrow(self):
        return self.execute().to_arrow()

    def to_pandas(self):
        return self.execute().to_pandas()

    def collect(self) -> List[tuple]:
        table = self.to_arrow()
        return [tuple(d.values()) for d in table.to_pylist()]

    def count(self) -> int:
        return self.execute().num_rows

    def explain(self, verbose: bool = False) -> str:
        text = self.plan.tree_string()
        if self.session.is_hyperspace_enabled():
            # Diagnostic pass: explaining a plan must not count as index
            # usage or emit usage telemetry.
            text += "\n\n== Optimized (hyperspace) ==\n" + \
                self.session.optimize(self.plan,
                                      diagnostic=True).tree_string()
        return text

    def with_column(self, name: str, expr: E.Expr) -> "DataFrame":
        """Add or replace a column (Spark's withColumn: the column to
        REPLACE matches case-insensitively, but the output keeps the
        caller's spelling — Spark emits col.as(the user's name))."""
        resolved = self._spelling(name)
        expr = self._resolve_expr(expr)
        exprs = [E.Col(n) if n != resolved else expr.alias(name)
                 for n in self.plan.schema.names]
        if resolved not in self.plan.schema.names:
            exprs.append(expr.alias(name))
        return DataFrame(self.session, Project(exprs, self.plan))

    withColumn = with_column

    def with_window(self, name: str, wexpr: E.Expr) -> "DataFrame":
        """Append an analytic (window) column — the analogue of Spark's
        ``withColumn(name, fn.over(windowSpec))``; build ``wexpr`` with
        ``hyperspace_tpu.functions.window(...)``. The reference inherits
        window execution from Spark SQL; here it is a first-class plan
        node (plan/nodes.py Window)."""
        if not isinstance(wexpr, E.WindowExpr):
            raise HyperspaceException(
                f"with_window expects a WindowExpr; got {wexpr!r}")
        return DataFrame(self.session,
                         Window([(name, self._resolve_expr(wexpr))],
                                self.plan))

    def drop(self, *names: str) -> "DataFrame":
        dropped = {self._spelling(n) for n in names}
        keep = [n for n in self.plan.schema.names if n not in dropped]
        if not keep:
            raise HyperspaceException("drop() would remove every column")
        return DataFrame(self.session, Project(keep, self.plan))

    def distinct(self) -> "DataFrame":
        """Distinct rows, lowered onto the grouped-aggregation machinery
        (group by every column) so it inherits the index rewrites and the
        SPMD path."""
        cols = list(self.plan.schema.names)
        # Collision-proof count alias: an agg whose name matches a group
        # column would overwrite it in the executor's output dict.
        cnt = "__distinct_cnt"
        while cnt in cols:
            cnt += "_"
        agg = Aggregate(cols, [E.Count(None).alias(cnt)], self.plan)
        return DataFrame(self.session, Project(cols, agg))

    def union(self, other: "DataFrame") -> "DataFrame":
        if self.plan.schema.names != other.plan.schema.names:
            raise HyperspaceException(
                f"union() column mismatch: {self.plan.schema.names} vs "
                f"{other.plan.schema.names}")
        mismatched = [
            (f.name, f.dtype, other.plan.schema.field(f.name).dtype)
            for f in self.plan.schema.fields
            if f.dtype != other.plan.schema.field(f.name).dtype]
        if mismatched:
            raise HyperspaceException(
                f"union() dtype mismatch: {mismatched}")
        return DataFrame(self.session, Union([self.plan, other.plan]))

    unionAll = union

    @property
    def write(self) -> "DataFrameWriter":
        """Write the (rewritten) query result to files — the output side
        of the user API (Spark's df.write analogue)."""
        return DataFrameWriter(self)


class DataFrameWriter:
    """Minimal writer: result → parquet/csv/json files. ``mode``:
    "error" (default, refuse to overwrite a non-empty dir) |
    "overwrite" | "append" (add a new part file).

    ``bucket_by(n, cols...)`` (parquet only) writes a bucketed, per-bucket-
    sorted dataset through the same hash→bucket→sort pipeline the index
    build uses — the analogue of the reference's ``saveWithBuckets``
    (util/DataFrameWriterExtensions.scala): bucket ids are recoverable
    from the file names and rows within each file are sorted by the
    bucketing columns."""

    def __init__(self, df: "DataFrame"):
        self._df = df
        self._mode = "error"
        self._bucket = None  # (num_buckets, [cols]) once bucket_by is set
        self._partition = None  # [cols] once partition_by is set

    def mode(self, mode: str) -> "DataFrameWriter":
        if mode not in ("error", "overwrite", "append"):
            raise HyperspaceException(f"Unknown write mode: {mode}")
        self._mode = mode
        return self

    def bucket_by(self, num_buckets: int, *cols: str) -> "DataFrameWriter":
        if num_buckets <= 0:
            raise HyperspaceException(
                f"bucket_by needs a positive bucket count, got {num_buckets}")
        if not cols:
            raise HyperspaceException(
                "bucket_by needs at least one bucketing column")
        cols = tuple(self._df._spelling(c) for c in cols)
        missing = [c for c in cols if c not in self._df.plan.schema]
        if missing:
            raise HyperspaceException(
                f"bucket_by columns not in the result: {missing}; "
                f"available: {self._df.plan.schema.names}")
        if self._partition is not None:
            raise HyperspaceException(
                "bucket_by and partition_by cannot be combined")
        self._bucket = (num_buckets, list(cols))
        return self

    bucketBy = bucket_by

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        """Hive-partitioned layout (`col=value/` directories) — pairs with
        the reader's partition discovery/pruning (sources/partitions.py)."""
        if not cols:
            raise HyperspaceException(
                "partition_by needs at least one partition column")
        names = self._df.plan.schema.names
        cols = tuple(self._df._spelling(c) for c in cols)
        missing = [c for c in cols if c not in names]
        if missing:
            raise HyperspaceException(
                f"partition_by columns not in the result: {missing}; "
                f"available: {names}")
        if len(set(cols)) != len(cols):
            raise HyperspaceException(
                f"partition_by columns repeat: {list(cols)}")
        if len(set(cols)) == len(names):
            raise HyperspaceException(
                "partition_by cannot consume every output column")
        if self._bucket is not None:
            raise HyperspaceException(
                "bucket_by and partition_by cannot be combined")
        self._partition = list(cols)
        return self

    partitionBy = partition_by

    # Write protocol, in this order for every format:
    #   1. _check: cheap destination validation BEFORE the query runs
    #      (a refused write must not pay the plan's execution cost);
    #   2. materialize the result fully in memory;
    #   3. _finalize: only now delete (overwrite) + create the dir — so
    #      writing a query back over its own source is safe (the data was
    #      already read in step 2).

    def _check(self, path: str) -> None:
        if os.path.isfile(path):
            raise HyperspaceException(f"Path is a file, not a dir: {path}")
        if self._mode == "error" and os.path.isdir(path) and os.listdir(path):
            raise HyperspaceException(
                f"Path not empty: {path} (use mode('overwrite') or "
                "mode('append'))")

    def _prepare_dir(self, path: str) -> str:
        """Destination prep shared by all writers: delete (overwrite) and
        create the dir only AFTER the query result was materialized — so
        writing a query back over its own source is safe."""
        import shutil
        if self._mode == "overwrite" and os.path.isdir(path):
            shutil.rmtree(path)
        os.makedirs(path, exist_ok=True)
        return path

    def _finalize(self, path: str) -> str:
        import uuid
        self._prepare_dir(path)
        return os.path.join(path, f"part-{uuid.uuid4().hex[:12]}")

    BUCKET_SPEC_FILE = "_bucket_spec.json"  # invisible to readers (they
    #                                         list only format suffixes)

    def parquet(self, path: str) -> None:
        from .execution.columnar import write_parquet
        self._check(path)
        if self._bucket is not None:
            self._bucketed_parquet(path)
            return
        if self._partition is not None:
            self._guard_bucketed_dir(path)
            self._partitioned_parquet(path)
            return
        self._guard_bucketed_dir(path)
        table = self._df.execute().to_host()
        write_parquet(table, self._finalize(path) + ".parquet")

    def _partitioned_parquet(self, path: str) -> None:
        import uuid

        import pyarrow as pa
        import pyarrow.dataset as pa_ds

        at = self._df.to_arrow()  # materialize BEFORE destination prep
        part_schema = pa.schema([at.schema.field(c)
                                 for c in self._partition])
        self._prepare_dir(path)
        if at.num_rows == 0:
            # pa_ds.write_dataset emits NOTHING for 0 rows, leaving an
            # unreadable dir; a full-schema 0-row file keeps read-back
            # working (the bucketed writer does the same).
            import pyarrow.parquet as _pq
            if not any(f.endswith(".parquet")
                       for f in os.listdir(path)):
                _pq.write_table(
                    at, os.path.join(
                        path, f"part-{uuid.uuid4().hex[:12]}.parquet"))
            return
        pa_ds.write_dataset(
            at, path, format="parquet",
            partitioning=pa_ds.partitioning(part_schema, flavor="hive"),
            basename_template=(
                f"part-{uuid.uuid4().hex[:12]}-{{i}}.parquet"),
            existing_data_behavior="overwrite_or_ignore")

    def _bucketed_parquet(self, path: str) -> None:
        import json
        import uuid

        from .actions.create import _write_bucket_files
        from .ops import index_build

        num_buckets, cols = self._bucket
        spec_path = os.path.join(path, self.BUCKET_SPEC_FILE)
        if self._mode == "append" and os.path.isdir(path) and \
                os.listdir(path):
            # Appends must match the directory's existing bucket layout —
            # a different spec (or a previously unbucketed dir) would
            # silently put rows in files whose name promises a different
            # bucket (the recoverable-bucket-id contract).
            try:
                with open(spec_path) as f:
                    existing = json.load(f)
            except OSError:
                raise HyperspaceException(
                    f"Cannot bucket-append to {path}: it was not written "
                    "with bucket_by (no bucket spec found).") from None
            if existing != {"numBuckets": num_buckets, "columns": cols}:
                raise HyperspaceException(
                    f"bucket_by({num_buckets}, {cols}) does not match the "
                    f"existing layout of {path}: "
                    f"bucket_by({existing['numBuckets']}, "
                    f"{existing['columns']}).")
        table = self._df.execute()
        sorted_table, bounds = index_build.build_sorted_buckets(
            table, cols, num_buckets)
        host = sorted_table.to_host()
        self._prepare_dir(path)
        with open(spec_path, "w") as f:
            json.dump({"numBuckets": num_buckets, "columns": cols}, f)
        # A unique per-write suffix keeps Append-mode files from colliding;
        # the bucket id stays recoverable (bucket_id_from_file matches the
        # part-<id> prefix).
        suffix = uuid.uuid4().hex[:8]

        def name_for(bucket: int) -> str:
            return index_build.bucket_file_name(bucket).replace(
                ".parquet", f"-{suffix}.parquet")

        if host.num_rows == 0 and not any(
                f.endswith(".parquet") for f in os.listdir(path)):
            # Schema preservation for an empty result landing in an empty
            # dir: one 0-row file (read-back of a fileless dir would fail).
            from .execution.columnar import write_parquet
            write_parquet(host, os.path.join(path, name_for(0)))
            return
        _write_bucket_files(host, bounds, 0, num_buckets, path,
                            row_group_size=None, file_name=name_for)

    def csv(self, path: str) -> None:
        import pyarrow.csv as pa_csv
        self._check(path)
        self._reject_buckets("csv")
        at = self._df.to_arrow()
        pa_csv.write_csv(at, self._finalize(path) + ".csv")

    def _reject_buckets(self, fmt: str) -> None:
        if self._bucket is not None:
            raise HyperspaceException(
                f"bucket_by is only supported for parquet output, not {fmt}")
        if self._partition is not None:
            raise HyperspaceException(
                f"partition_by is only supported for parquet output, "
                f"not {fmt}")

    def _guard_bucketed_dir(self, path: str) -> None:
        """Non-bucketed writes must not land inside a bucketed dataset."""
        if self._mode == "append" and \
                os.path.isfile(os.path.join(path, self.BUCKET_SPEC_FILE)):
            raise HyperspaceException(
                f"{path} holds a bucketed dataset; appending "
                "non-bucketed rows would break its layout. Use "
                "bucket_by(<same spec>) or mode('overwrite').")

    def json(self, path: str) -> None:
        self._check(path)
        self._reject_buckets("json")
        df = self._df.to_pandas()
        df.to_json(self._finalize(path) + ".json",
                   orient="records", lines=True, date_format="iso")

    def avro(self, path: str) -> None:
        from .util.avro import write_avro
        self._check(path)
        self._reject_buckets("avro")
        at = self._df.to_arrow()
        write_avro(at, self._finalize(path) + ".avro")


class GroupedData:
    def __init__(self, df: DataFrame, group_cols: List[str]):
        self._df = df
        self._group_cols = group_cols

    def agg(self, *aggs: E.Expr) -> DataFrame:
        return DataFrame(self._df.session,
                         Aggregate(self._group_cols,
                                   [self._df._resolve_expr(a) for a in aggs],
                                   self._df.plan))

    def count(self) -> DataFrame:
        return self.agg(E.Count(None))
