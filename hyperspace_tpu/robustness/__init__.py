"""Robustness layer: fault injection, deadlines, retry, degradation.

Four legs over the whole engine (see ISSUE r11 / ROADMAP item 5's
prerequisites — a cross-process call that cannot time out, retry, or
degrade cannot ship):

- ``faults``   — named fault points at every risky boundary, armed via
  ``hyperspace.tpu.robustness.faults.*`` conf, hard no-op disarmed;
- ``retry``    — bounded exponential-backoff retry for transient
  errors at idempotent boundaries (pooled reads, op-log writes);
- deadlines    — per-query cooperative cancellation
  (serving/context.check_deadline at stage/io/dispatch boundaries);
- ``recovery`` — crash recovery for a lake another process died in
  (transient-state rollback + orphaned data-version vacuum).

The degradation ladders themselves live at their fault sites (executor
SPMD fallback, program-bank eager path, result-cache spill handling,
frontend member/worker release); this package provides the machinery
that arms, observes, and proves them.
"""

# Only the light fault core is re-exported: config.py imports this
# package for its constants, so the package import must not drag the
# index/action stack in (recovery is imported lazily by its callers).
from .faults import (FaultRegistry, FaultSpec, InjectedFaultError,  # noqa: F401
                     TransientInjectedFaultError, fault_point)
