"""Process-wide fault-injection registry.

The robustness layer's first leg: named fault points (the frozen
vocabulary of fault_names.py) instrumented at every risky boundary —
pooled reads and prefetch producers, parquet decode, SPMD compile +
dispatch, program-bank compile, result-cache device_put and spill
read-back, op-log writes, action bodies, and serving workers — armed
via ``hyperspace.tpu.robustness.faults.<point>`` conf and compiled to a
hard no-op while disarmed: :func:`fault_point` is ONE contextvar read
returning immediately (the r13 tracing-off precedent), so production
paths pay effectively nothing.

Arming is SCOPED, not global: ``Session.execute`` and ``Action.run``
build one :class:`FaultRegistry` per run from the governing conf
(:func:`scope_for`), so ``nth=``/``times=`` counters are deterministic
per query / per action, and concurrent sessions with different fault
confs never see each other's injections. The registry rides the
contextvar across serving workers and prefetch producers exactly like
the trace/io scopes it sits beside; reader-pool workers (which never
inherit the context) get the registry handed in explicitly
(``fault_point(name, reg=...)``) by the consumer that captured it.

Spec grammar (the conf value): ``kind[:opt=val[,opt=val...]]`` —
see robustness/constants.py for kinds and options. ``kill`` SIGKILLs
the process at the point, which is how the crash-recovery harness
produces a real mid-action ``kill -9`` at an exact protocol position.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import random
import signal
import threading
import time
from typing import Dict, Optional

from ..exceptions import HyperspaceException
from . import fault_names


class InjectedFaultError(HyperspaceException):
    """The typed error an armed ``error`` fault point raises — a
    HyperspaceException subclass, so chaos runs can assert every failed
    submission surfaced a typed framework error."""


class TransientInjectedFaultError(InjectedFaultError):
    """An armed ``transient`` fault: classified retryable by
    robustness/retry.py alongside OSError/TimeoutError."""


_KINDS = ("error", "transient", "latency", "kill")

# Builtin exception classes an ``error:exc=<name>`` spec may name.
_EXC_CLASSES = {
    "OSError": OSError,
    "IOError": OSError,
    "TimeoutError": TimeoutError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
    "MemoryError": MemoryError,
}


class FaultSpec:
    """One parsed fault-point arming."""

    __slots__ = ("name", "kind", "p", "nth", "times", "ms", "exc")

    def __init__(self, name: str, kind: str, p: float = 1.0,
                 nth: Optional[int] = None, times: Optional[int] = None,
                 ms: float = 50.0, exc=None):
        self.name = name
        self.kind = kind
        self.p = p
        self.nth = nth
        self.times = times
        self.ms = ms
        self.exc = exc

    @classmethod
    def parse(cls, name: str, raw: str) -> "FaultSpec":
        if name not in fault_names.FAULT_NAMES:
            raise HyperspaceException(
                f"Unknown fault point {name!r}; names come from the "
                "frozen robustness/fault_names.py registry: "
                f"{sorted(fault_names.FAULT_NAMES)}")
        raw = (raw or "").strip()
        kind, _, opts_raw = raw.partition(":")
        kind = kind.strip().lower()
        if kind not in _KINDS:
            raise HyperspaceException(
                f"Unknown fault kind {kind!r} for point {name!r}; "
                f"expected one of {_KINDS} "
                "(spec: kind[:opt=val[,opt=val...]])")
        spec = cls(name, kind)
        for part in filter(None, (p.strip() for p in opts_raw.split(","))):
            k, eq, v = part.partition("=")
            if not eq:
                raise HyperspaceException(
                    f"Malformed fault option {part!r} for point {name!r}")
            k = k.strip().lower()
            v = v.strip()
            if k == "p":
                spec.p = min(max(float(v), 0.0), 1.0)
            elif k == "nth":
                spec.nth = max(int(v), 1)
            elif k == "times":
                spec.times = max(int(v), 0)
            elif k == "ms":
                spec.ms = max(float(v), 0.0)
            elif k == "exc":
                exc = _EXC_CLASSES.get(v)
                if exc is None:
                    raise HyperspaceException(
                        f"Unknown exception class {v!r} for fault point "
                        f"{name!r}; supported: "
                        f"{sorted(_EXC_CLASSES)}")
                spec.exc = exc
            else:
                raise HyperspaceException(
                    f"Unknown fault option {k!r} for point {name!r}")
        return spec


class FaultRegistry:
    """The armed fault points of one scope (one query / one action run).
    ``trigger`` counts every hit per point and fires per the spec;
    counters live here, so nth/times semantics are scope-deterministic."""

    def __init__(self, specs: Dict[str, FaultSpec], seed: int = 0):
        self._specs = dict(specs)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._hits = {n: 0 for n in specs}
        self._fired = {n: 0 for n in specs}

    @classmethod
    def from_conf_specs(cls, raw_specs: Dict[str, str],
                        seed: int = 0) -> "FaultRegistry":
        return cls({n: FaultSpec.parse(n, raw) for n, raw
                    in raw_specs.items()}, seed=seed)

    def hit_count(self, name: str) -> int:
        with self._lock:
            return self._hits.get(name, 0)

    def trigger(self, name: str) -> None:
        spec = self._specs.get(name)
        if spec is None:
            return
        with self._lock:
            self._hits[name] += 1
            hit = self._hits[name]
            if spec.nth is not None and hit != spec.nth:
                return
            if spec.times is not None and self._fired[name] >= spec.times:
                return
            if spec.p < 1.0 and self._rng.random() >= spec.p:
                return
            self._fired[name] += 1
        note(injected=1)
        if spec.kind == "latency":
            time.sleep(spec.ms / 1000.0)
            return
        if spec.kind == "kill":
            # The crash harness's mid-action kill -9: immediate,
            # unhandleable, no atexit/flush — exactly a hard crash.
            os.kill(os.getpid(), signal.SIGKILL)
        if spec.kind == "transient":
            raise TransientInjectedFaultError(
                f"injected transient fault at {name!r}")
        exc = spec.exc if spec.exc is not None else InjectedFaultError
        raise exc(f"injected fault at {name!r}")


# ---------------------------------------------------------------------------
# The ambient armed scope (contextvar — follows the query across serving
# workers and prefetch producers like the trace/io scopes).
# ---------------------------------------------------------------------------

_ARMED: contextvars.ContextVar = contextvars.ContextVar(
    "hst_armed_faults", default=None)


def armed() -> Optional[FaultRegistry]:
    """The active registry, or None while disarmed. Consumers that fan
    work out to context-less pool threads capture this once and hand it
    to ``fault_point(name, reg=...)`` inside the task."""
    return _ARMED.get()


def fault_point(name: str, reg: Optional[FaultRegistry] = None) -> None:
    """Declare one named risky boundary. Disarmed (the default) this is
    a single contextvar read; armed, the registry decides whether to
    raise / sleep / kill here per the point's conf spec."""
    r = reg if reg is not None else _ARMED.get()
    if r is None:
        return
    r.trigger(name)


@contextlib.contextmanager
def scope(registry: Optional[FaultRegistry]):
    """Activate ``registry`` on this context (None = explicit no-op)."""
    if registry is None:
        yield None
        return
    token = _ARMED.set(registry)
    try:
        yield registry
    finally:
        _ARMED.reset(token)


# Per-arming scope counter: conf-armed registries are built fresh per
# run, so p= specs must NOT replay the identical RNG sequence every
# query (that would make "p=0.5" fire for either 100% or 0% of queries).
# Deriving each scope's seed from (conf seed, scope ordinal) keeps a
# single-threaded run replayable while giving real per-query sampling.
_SCOPE_IDS = itertools.count(1)


@contextlib.contextmanager
def scope_for(hs_conf):
    """Arm from the governing conf for one run (Session.execute /
    Action.run). No ``robustness.faults.*`` keys set — the overwhelmingly
    common case — skips registry construction AND the contextvar write
    entirely: the scope costs one small dict scan per run."""
    raw_specs = hs_conf.robustness_fault_specs()
    if not raw_specs:
        yield None
        return
    registry = FaultRegistry.from_conf_specs(
        raw_specs,
        seed=hs_conf.robustness_seed() * 1_000_003 + next(_SCOPE_IDS))
    token = _ARMED.set(registry)
    try:
        yield registry
    finally:
        _ARMED.reset(token)


def degrade_enabled() -> bool:
    """The ``robustness.degrade.enabled`` master switch of the governing
    session — the active QueryContext's, else the parallel-io session
    scope's (actions), else the default (on). Every degradation ladder
    asks HERE so fail-loud debugging disables all of them uniformly."""
    from ..serving.context import active_context
    ctx = active_context()
    session = ctx.session if ctx is not None else None
    if session is None:
        from ..parallel import io as pio
        session = pio.active_session()
    if session is None:
        return True
    return session.hs_conf.robustness_degrade_enabled()


# ---------------------------------------------------------------------------
# Process-wide robustness counters (explain's "Robustness:" section, the
# "robustness" collector in the metrics registry, bench assertions).
# ---------------------------------------------------------------------------

_COUNTER_KEYS = (
    "injected",                # fault points that actually fired
    "retries",                 # individual retry attempts that ran
    "retry_failures",          # retry sequences that exhausted attempts
    "deadline_cancellations",  # queries cancelled at a deadline check
    "degraded_spmd",           # SPMD faults absorbed by single-device
    "degraded_bank_compile",   # bank-compile faults -> uncached eager
    "degraded_device_put",     # device-tier put faults -> host tier
    "spill_corruptions",       # corrupt spill files served as misses
    "artifact_corruptions",    # corrupt artifact blobs served as misses
    "member_fallbacks",        # sweep members re-run standalone
    "worker_releases",         # entries released from a dying worker
    "recovered_indexes",       # transient op-log states rolled back
    "vacuumed_orphans",        # orphaned index data versions removed
)


class _Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {k: 0 for k in _COUNTER_KEYS}

    def note(self, **deltas) -> None:
        with self._lock:
            for k, v in deltas.items():
                self._counts[k] = self._counts.get(k, 0) + v

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            for k in self._counts:
                self._counts[k] = 0


_STATS = _Stats()

# Incidents that make the in-flight query's trace worth keeping under
# head sampling (telemetry/trace.py tail-keep): anything that means the
# query was one of the unlucky ones. Deliberately NOT the recovery
# counters — a maintenance sweep is not a query anomaly.
_TAIL_KEEP_KEYS = frozenset({
    "injected", "retries", "retry_failures", "deadline_cancellations",
    "degraded_spmd", "degraded_bank_compile", "degraded_device_put",
    "spill_corruptions", "artifact_corruptions", "member_fallbacks",
    "worker_releases",
})
# The subset that flips the active QueryContext's ``degraded`` flag
# (the SLO degrade-rate objective's per-query signal).
_DEGRADE_KEYS = frozenset({
    "degraded_spmd", "degraded_bank_compile", "degraded_device_put",
    "spill_corruptions", "member_fallbacks",
})


def note(**deltas) -> None:
    _STATS.note(**deltas)
    fired = {k for k, v in deltas.items() if v}
    if not (fired & _TAIL_KEEP_KEYS):
        return
    try:
        from ..telemetry import trace as _trace
        _trace.keep_active("robustness")
        if fired & _DEGRADE_KEYS:
            from ..serving.context import active_context
            ctx = active_context()
            if ctx is not None:
                ctx.degraded = True
    except Exception:
        pass  # observability must never mask the incident being noted


def stats() -> dict:
    """Process-lifetime robustness counters."""
    return _STATS.snapshot()


def reset_stats() -> None:
    """Zero the counters (bench A/B phases; never needed for
    correctness)."""
    _STATS.reset()


# The robustness counters are a named collector in the process metrics
# registry (telemetry/metrics.py), beside io/program_bank/serving.
from ..telemetry import metrics as _metrics  # noqa: E402

_metrics.get_registry().register_collector("robustness", stats)
