"""Crash recovery: op-log rollback + orphaned data-version vacuum.

The op-log protocol (actions/action.py) already guarantees a crashed
action leaves only a TRANSIENT log state: queries keep serving the last
stable entry, and ``cancel`` rolls the log back. What nothing owned
until now is the sweep a fresh session runs over a lake another process
died in — finding the wrecks and cleaning up the bytes:

- every index whose latest log entry is transient (CREATING /
  REFRESHING / OPTIMIZING / VACUUMING / ...) is rolled back to its last
  stable state via the existing CancelAction (the protocol's own
  recovery primitive, so concurrency control still applies);
- index data version directories (``v__=<n>``) referenced by NO
  ACTIVE/DELETED log entry are the dead action's partial output —
  immutable-version layout means they can never be served, so they are
  deleted (the "partial data files are vacuumed" half of crash safety).

Conservative by construction: version references are collected from
EVERY parseable log entry in a live state (not just the latest), so a
version any historical stable entry names survives; only directories no
entry has ever committed are removed. Proven by the kill -9 harness in
tests/test_crash_recovery.py across create/refresh/optimize/vacuum at
every op-log fault point.

Scope: filesystem-backed lakes (the index enumeration walks the system
path). Object-store deployments run the same per-index recovery through
``recover_index`` with their own listing.

OPERATOR ACTION: the op log records no liveness, so a transient entry
left by a crash is indistinguishable from one a LIVE action holds right
now — run the sweep only when no other process is mutating the lake
(the same contract as ``cancel``, which this drives).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from ..index.constants import IndexConstants, STABLE_STATES, States
from ..index.data_manager import IndexDataManager
from ..index.log_manager import IndexLogManager
from . import faults as _faults

# v__=<n> anywhere in a content file path names the data version the
# entry serves from.
_VERSION_RE = re.compile(
    re.escape(IndexConstants.INDEX_VERSION_DIRECTORY_PREFIX) + r"=(\d+)")


def recover_indexes(session, names: Optional[List[str]] = None) -> Dict:
    """Sweep every index under the session's system path (or just
    ``names``): roll back transient states, vacuum orphaned data
    versions. Returns a summary dict; per-index failures are collected
    under ``errors`` so one wrecked index cannot block the sweep."""
    summary: Dict = {"scanned": [], "cancelled": [], "vacuumed": {},
                     "errors": {}}
    root = session.hs_conf.system_path()
    if not os.path.isdir(root):
        return summary
    for name in sorted(os.listdir(root)):
        if names is not None and name not in names:
            continue
        index_path = os.path.join(root, name)
        if not os.path.isdir(
                os.path.join(index_path, IndexConstants.HYPERSPACE_LOG)):
            continue
        summary["scanned"].append(name)
        try:
            recover_index(session, index_path, name, summary)
        except Exception as e:
            summary["errors"][name] = f"{type(e).__name__}: {e}"
    if names is None:
        # Streaming-tier sweep (streaming/ingest.py): undo/redo torn
        # commits recorded in the per-table logs under _streaming/ and
        # clear staging leftovers (the dead appender's invisible files).
        try:
            from ..streaming.ingest import recover_streaming
            recover_streaming(session, summary)
        except Exception as e:
            summary["errors"]["_streaming"] = f"{type(e).__name__}: {e}"
    if summary["cancelled"] or summary["vacuumed"]:
        # A sweep that actually found wrecks IS the incident record:
        # another process died mid-action. Flight-recorder anomaly so
        # the post-mortem dump carries it.
        try:
            from ..telemetry.flight_recorder import note_anomaly
            note_anomaly(
                "crash.recovery",
                f"cancelled={summary['cancelled']} "
                f"vacuumed={sorted(summary['vacuumed'])}")
        except Exception:
            pass
    return summary


def recover_index(session, index_path: str, name: str,
                  summary: Optional[Dict] = None) -> Dict:
    """Recover ONE index directory; see :func:`recover_indexes`."""
    if summary is None:
        summary = {"scanned": [name], "cancelled": [], "vacuumed": {},
                   "errors": {}}
    mgr = IndexLogManager(index_path)
    latest_id = mgr.get_latest_id()
    if latest_id is None:
        return summary
    latest = mgr._get_log_lenient(latest_id)
    if latest is not None and latest.state not in STABLE_STATES:
        from ..actions.lifecycle import CancelAction
        CancelAction(session, mgr, IndexDataManager(index_path)).run()
        summary["cancelled"].append(name)
        _faults.note(recovered_indexes=1)
    orphans = _vacuum_orphan_versions(mgr, index_path)
    if orphans:
        summary["vacuumed"][name] = orphans
        _faults.note(vacuumed_orphans=len(orphans))
    return summary


def _referenced_versions(mgr: IndexLogManager) -> set:
    """Data versions any parseable ACTIVE/DELETED entry commits to.
    DOESNOTEXIST and transient entries reference nothing servable — a
    crashed action's entry must not protect its own partial output.
    Iterates the EXISTING ids (sparse after compaction), not a dense
    range — see IndexLogManager.get_all_ids."""
    referenced: set = set()
    for log_id in mgr.get_all_ids():
        entry = mgr._get_log_lenient(log_id)
        if entry is None or entry.state not in (States.ACTIVE,
                                                States.DELETED):
            continue
        try:
            files = entry.content.files
        except Exception:
            continue  # a content-less entry constrains nothing
        for f in files:
            for m in _VERSION_RE.finditer(f):
                referenced.add(int(m.group(1)))
    return referenced


def _vacuum_orphan_versions(mgr: IndexLogManager,
                            index_path: str) -> List[int]:
    if mgr.get_latest_id() is None:
        return []
    referenced = _referenced_versions(mgr)
    dm = IndexDataManager(index_path)
    orphans = [v for v in dm.get_all_version_ids() if v not in referenced]
    for v in orphans:
        dm.delete(v)
    return orphans
