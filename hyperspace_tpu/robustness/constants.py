"""Robustness-layer config keys + defaults (`hyperspace.tpu.robustness.*`).

No reference analogue: the reference delegated all fault tolerance —
task retry, speculative execution, atomic commit — to Spark (PAPER.md);
owning it is the point of this reproduction. Keys follow the
conf-string convention of ``index/constants.py`` and are read ONLY
through config.py accessors (the scripts/lint.py env gate).
"""

from __future__ import annotations


class RobustnessConstants:
    # Fault-injection arming: one key PER fault point, spelled
    # ``hyperspace.tpu.robustness.faults.<point>`` where <point> comes
    # from the frozen robustness/fault_names.py registry. The value is a
    # spec string ``kind[:opt=val[,opt=val...]]``:
    #   kinds  error (typed InjectedFaultError, or exc=<builtin name>),
    #          transient (retryable TransientInjectedFaultError),
    #          latency (sleep ms, then proceed),
    #          kill (SIGKILL the process — the crash harness's kill -9)
    #   opts   p=<0..1> probability, nth=<n> fire only on the nth hit,
    #          times=<k> fire at most k times, ms=<n> latency duration,
    #          exc=<name> builtin exception class for kind=error
    # Unset (the default) compiles every fault point to a hard no-op:
    # one contextvar read, nothing armed, byte-identical results.
    FAULTS_PREFIX = "hyperspace.tpu.robustness.faults"

    # Seed of the per-arming RNG behind probabilistic (p=) specs, so a
    # chaos run replays deterministically.
    SEED = "hyperspace.tpu.robustness.seed"
    SEED_DEFAULT = "0"

    # Per-query cooperative deadline in milliseconds (0 = none). Applies
    # to every Session.execute on the session; ServingFrontend.submit's
    # explicit ``deadline_ms=`` overrides per submission (measured from
    # submit time, so queue wait counts). Expiry raises the typed
    # QueryDeadlineError at the next stage/io/dispatch boundary.
    DEADLINE_MS = "hyperspace.tpu.robustness.deadlineMs"
    DEADLINE_MS_DEFAULT = "0"

    # Transient-fault retry (pooled reader tasks, op-log store writes):
    # up to maxAttempts total attempts with exponential backoff starting
    # at baseMs (jittered). maxAttempts=1 disables retry entirely.
    RETRY_MAX_ATTEMPTS = "hyperspace.tpu.robustness.retry.maxAttempts"
    RETRY_MAX_ATTEMPTS_DEFAULT = "3"
    RETRY_BASE_MS = "hyperspace.tpu.robustness.retry.baseMs"
    RETRY_BASE_MS_DEFAULT = "10"

    # Master switch of the graceful-degradation ladders (SPMD dispatch /
    # compile failure -> single-device re-execution; program-bank
    # compile failure -> uncached eager path; sweep-member failure ->
    # per-member re-execution; result-cache device_put failure -> host
    # tier). Off = failures propagate as-is (debugging).
    DEGRADE_ENABLED = "hyperspace.tpu.robustness.degrade.enabled"
    DEGRADE_ENABLED_DEFAULT = "true"
