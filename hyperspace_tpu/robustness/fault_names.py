"""Frozen registry of fault-point names.

Every ``faults.fault_point(...)`` site in the package must name its
point with one of these constants — free-form strings are rejected by
the scripts/lint.py fault-discipline gate, and every name registered
here must be referenced under tests/ (an uninjected fault point is
unverified robustness, the same contract the span-discipline gate
enforces for telemetry/span_names.py).

A fault point marks ONE risky boundary: the exact program position a
crash, a transient I/O error, or injected latency is allowed to strike
when the point is armed via ``hyperspace.tpu.robustness.faults.<name>``
conf. Keep the vocabulary SMALL and stable — the chaos soak, the crash
harness, and the degradation-ladder tests all key on these strings.
"""

from __future__ import annotations

# One pooled reader task (parallel/io.py imap_ordered): fires inside
# the retried read fn, so transient injections exercise the retry path
# on worker threads and on the sequential fallback alike.
IO_POOLED_READ = "io.pooled_read"

# The prefetch producer advancing its source one item (parallel/io.py
# prefetch_iter) — errors cross the queue and surface at the consumer.
IO_PREFETCH_PRODUCE = "io.prefetch_produce"

# Multi-file scan decode (execution/columnar.read_parquet entry — every
# format funnels through it).
SCAN_PARQUET_DECODE = "scan.parquet_decode"

# SPMD mesh dispatch (execution/spmd._run/_run_stream) and the AOT
# compile of one mesh executable (parallel/sharding.MeshProgram).
# Failures here prove the SPMD -> single-device degradation ladder.
SPMD_DISPATCH = "spmd.dispatch"
SPMD_COMPILE = "spmd.compile"

# Program-bank wrapper construction (serving/program_bank.lookup):
# failure degrades to the uncached eager path.
BANK_COMPILE = "bank.compile"

# Result-cache residency moves (serving/result_cache): the batched
# device_put on device-tier admission, and the disk-spill read-back
# (corruption here must be a miss, never a wrong answer).
RESULT_CACHE_DEVICE_PUT = "result_cache.device_put"
RESULT_CACHE_SPILL_READ = "result_cache.spill_read"

# Op-log writes (index/log_manager): the conditional entry put and the
# latestStable overwrite — the crash-recovery harness kill -9s here.
LOG_WRITE = "log.write"
LOG_STABLE = "log.stable"

# The start of an action's op() body (actions/action.py): a crash here
# leaves the transient log state with partial (or no) index data.
ACTION_OP = "action.op"

# A serving worker between popping an entry and executing it
# (serving/frontend._drain): death here must release held members to
# per-member execution, never strand their futures. Arming scope: the
# point fires under the HEAD entry's SUBMIT-time context snapshot, so
# arm it with an explicit ``faults.scope(registry)`` around the
# submits (one registry per submission wave — worker death is a
# property of the workload, not of one query's conf); per-execute conf
# arming happens after this point and cannot reach it.
SERVING_WORKER = "serving.worker"

# Streaming ingestion (streaming/ingest.py). INGEST_STAGE fires inside
# append() before the staged batch parquet is written (a crash here
# leaves only an invisible staging orphan the recovery sweep deletes);
# INGEST_PUBLISH fires inside the commit action's op() after the
# transient table-log entry landed but before any batch file moves —
# the canonical mid-commit wreck the kill -9 harness strikes, proving
# recover() rolls the staged batch back.
INGEST_STAGE = "ingest.stage"
INGEST_PUBLISH = "ingest.publish"

# Artifact-store boundaries (artifacts/store.py). ARTIFACTS_WRITE fires
# between the publication temp write and the link-into-place — the
# kill -9 harness strikes here to prove no torn blob is ever loadable;
# an injected error costs only persistence. ARTIFACTS_READ fires before
# the blob read: injected errors must be silent misses (a normal
# compile follows), never query failures.
ARTIFACTS_WRITE = "artifacts.write"
ARTIFACTS_READ = "artifacts.read"

# Continuous-source poll body (streaming/sources.py _poll_once): fires
# before the source scans for new input — an injected error must cost
# only that poll (counted, backed off, retried next tick), never kill
# the tailer daemon or tear staged state.
STREAMING_SOURCE = "streaming.source"

# Serving cluster (cluster/worker.py). CLUSTER_FORWARD fires on the
# sender side before a routed submission ships to its shard owner — an
# injected error must degrade to local execution (byte-identical), the
# r14 ladder applied to the network. CLUSTER_BROADCAST fires before
# each peer's commit notice — an injected error costs only that peer's
# standing-query firing, never the commit itself.
CLUSTER_FORWARD = "cluster.forward"
CLUSTER_BROADCAST = "cluster.broadcast"

# Buffer-pool probe/load boundary (execution/buffer_pool.py get()):
# fires before a cached decoded buffer is served. An injected (or real)
# load failure under the degrade contract is a SILENT MISS — the entry
# is dropped and the caller re-reads from parquet, never a wrong
# answer; with degrade disabled it fails loud.
BUFFER_LOAD = "buffer.load"

FAULT_NAMES = frozenset({
    IO_POOLED_READ, IO_PREFETCH_PRODUCE, SCAN_PARQUET_DECODE,
    SPMD_DISPATCH, SPMD_COMPILE, BANK_COMPILE,
    RESULT_CACHE_DEVICE_PUT, RESULT_CACHE_SPILL_READ,
    LOG_WRITE, LOG_STABLE, ACTION_OP, SERVING_WORKER,
    INGEST_STAGE, INGEST_PUBLISH, STREAMING_SOURCE,
    ARTIFACTS_WRITE, ARTIFACTS_READ,
    CLUSTER_FORWARD, CLUSTER_BROADCAST, BUFFER_LOAD,
})
