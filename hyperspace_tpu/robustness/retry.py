"""Bounded retry with exponential backoff + jitter for transient faults.

The robustness layer's third leg, wrapped around exactly the boundaries
where a transient error is both plausible and safe to repeat: pooled
reader tasks (parallel/io.py — file reads are idempotent, and the
ordered gather keeps results byte-identical whether attempt 1 or 3
produced them) and op-log store writes (index/log_manager.py — the
conditional put decides every race, so re-putting after an OSError is
the protocol's own semantics).

Transient means: OSError/TimeoutError (the real I/O failure classes)
or an injected :class:`~.faults.TransientInjectedFaultError`. Anything
else propagates on the FIRST attempt — retrying a deterministic error
only doubles the damage. A sequence that exhausts its attempts
surfaces the ORIGINAL error (the first failure is the diagnosis; later
attempts' errors are noise from a degrading system).

Policy comes from ``hyperspace.tpu.robustness.retry.{maxAttempts,
baseMs}`` via config.py; delays are ``baseMs * 2^(attempt-1)`` jittered
uniformly in [0.5x, 1.5x) so synchronized retry storms decorrelate. A
query past its deadline never sleeps here — ``check_deadline`` runs
before each backoff.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

from . import faults as _faults

# The exception classes a retry may absorb. ConnectionError/InterruptedError
# are OSError subclasses; everything else is assumed deterministic.
TRANSIENT_TYPES = (OSError, TimeoutError,
                   _faults.TransientInjectedFaultError)

# OSError subclasses that are DETERMINISTIC, not flaky-I/O: a missing
# file or a permission wall fails identically on every attempt —
# retrying only delays the real error and pollutes the retry telemetry.
NON_TRANSIENT_TYPES = (FileNotFoundError, NotADirectoryError,
                       IsADirectoryError, PermissionError)


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 3
    base_ms: float = 10.0


DEFAULT_POLICY = RetryPolicy()


def policy_from_conf(hs_conf) -> RetryPolicy:
    return RetryPolicy(
        max_attempts=max(int(hs_conf.robustness_retry_max_attempts()), 1),
        base_ms=max(float(hs_conf.robustness_retry_base_ms()), 0.0))


def active_policy() -> RetryPolicy:
    """Policy of the governing session: the active QueryContext's, else
    the parallel-io session scope's (actions run under it), else the
    defaults."""
    from ..parallel import io as pio
    from ..serving.context import active_context
    ctx = active_context()
    session = ctx.session if ctx is not None else pio.active_session()
    if session is not None:
        return policy_from_conf(session.hs_conf)
    return DEFAULT_POLICY


def call(fn: Callable, *, where: str = "", policy: Optional[RetryPolicy]
         = None, session=None):
    """Run ``fn()`` with up to ``policy.max_attempts`` attempts,
    absorbing transient errors between them. Emits one RetryEvent per
    sequence that retried (success or exhaustion) and feeds the
    process-wide robustness counters."""
    p = policy if policy is not None else active_policy()
    first_err: Optional[BaseException] = None
    for attempt in range(1, p.max_attempts + 1):
        try:
            result = fn()
        except TRANSIENT_TYPES as e:
            if isinstance(e, NON_TRANSIENT_TYPES):
                raise  # deterministic: fail now, with the real error
            if first_err is None:
                first_err = e
            if attempt >= p.max_attempts:
                _faults.note(retries=attempt - 1, retry_failures=1)
                _emit(session, where, attempt, False, first_err)
                raise first_err
            # A cancelled query must not sleep through a backoff.
            from ..serving.context import check_deadline
            check_deadline(where)
            delay_s = (p.base_ms / 1000.0) * (2 ** (attempt - 1))
            if delay_s > 0:
                time.sleep(delay_s * (0.5 + random.random()))
            continue
        if attempt > 1:
            _faults.note(retries=attempt - 1)
            _emit(session, where, attempt, True, first_err)
        return result


def _emit(session, where: str, attempts: int, succeeded: bool,
          first_err: Optional[BaseException]) -> None:
    """One RetryEvent per retried sequence, through the governing
    session's logger (the explicit one, else the parallel-io scope's)."""
    try:
        if session is None:
            from ..parallel import io as pio
            session = pio.active_session()
        if session is None:
            from ..serving.context import active_context
            ctx = active_context()
            session = ctx.session if ctx is not None else None
        if session is None:
            return
        from ..telemetry.events import RetryEvent
        from ..telemetry.logging import get_logger
        get_logger(session.hs_conf.event_logger_class()).log_event(
            RetryEvent(
                message=(f"retry at {where!r}: {attempts} attempt(s), "
                         + ("recovered" if succeeded else "exhausted")),
                where=where, attempts=attempts, succeeded=succeeded,
                error=(f"{type(first_err).__name__}: {first_err}"
                       if first_err is not None else "")))
    except Exception:
        pass  # observability must never fail the retried operation
