"""Minimal Avro Object Container File reader/writer (no external deps).

Closes the one source-format gap vs the reference's default provider
(sources/default/DefaultFileBasedSource.scala:37-44 supports
avro/csv/json/orc/parquet/text): this image ships no avro library, so the
subset of the Avro 1.x spec that tabular data uses is implemented here
directly — records of primitives, nullable fields as ``["null", T]``
unions, the ``date`` logical type, and the null/deflate codecs. Arrays,
maps, nested records, and enums are out of scope and rejected loudly.

Everything converts to/from ``pyarrow.Table`` at the boundary, so the
columnar engine sees avro exactly like any other format.
"""

from __future__ import annotations

import datetime
import io
import json
import struct
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ..exceptions import HyperspaceException

_MAGIC = b"Obj\x01"
_EPOCH = datetime.date(1970, 1, 1)

_PRIMITIVES = ("null", "boolean", "int", "long", "float", "double",
               "string", "bytes")


# ---------------------------------------------------------------------------
# Binary decoding.
# ---------------------------------------------------------------------------

class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise HyperspaceException("avro: truncated data")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def read_long(self) -> int:
        """Zigzag varint (avro int and long share the encoding)."""
        shift = 0
        acc = 0
        while True:
            if self.pos >= len(self.buf):
                raise HyperspaceException("avro: truncated data")
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
            if shift > 70:
                raise HyperspaceException("avro: varint too long")
        return (acc >> 1) ^ -(acc & 1)

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)


def _encode_long(n: int) -> bytes:
    n = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode_bytes(b: bytes) -> bytes:
    return _encode_long(len(b)) + b


# ---------------------------------------------------------------------------
# Schema handling.
# ---------------------------------------------------------------------------

def _field_plan(ftype) -> Tuple[str, Optional[int], Optional[str]]:
    """Normalize a field's avro type into (primitive, null_branch, logical)
    where null_branch is the union index of "null" (None for non-nullable
    fields — branch order matters at decode time, both ["null", T] and
    [T, "null"] are legal). Raises for shapes outside the tabular subset."""
    logical = None
    if isinstance(ftype, dict):
        logical = ftype.get("logicalType")
        ftype = ftype.get("type")
        if logical not in (None, "date"):
            logical = None  # other logical types decode as their base type
        if not isinstance(ftype, str):
            raise HyperspaceException(
                f"avro: unsupported complex type {ftype!r}")
        if ftype not in _PRIMITIVES:
            raise HyperspaceException(f"avro: unsupported type {ftype!r}")
        return ftype, None, logical
    if isinstance(ftype, str):
        if ftype not in _PRIMITIVES:
            raise HyperspaceException(f"avro: unsupported type {ftype!r}")
        return ftype, None, None
    if isinstance(ftype, list):
        branches = [t for t in ftype if t != "null"]
        if len(ftype) != 2 or len(branches) != 1:
            raise HyperspaceException(
                f"avro: only two-branch null unions supported, got {ftype!r}")
        null_branch = ftype.index("null")
        prim, _, logical = _field_plan(branches[0])
        return prim, null_branch, logical
    raise HyperspaceException(f"avro: unsupported type {ftype!r}")


def _arrow_type(prim: str, logical: Optional[str]) -> pa.DataType:
    if logical == "date":
        return pa.date32()
    return {
        "boolean": pa.bool_(), "int": pa.int32(), "long": pa.int64(),
        "float": pa.float32(), "double": pa.float64(),
        "string": pa.string(), "bytes": pa.binary(),
        "null": pa.null(),
    }[prim]


def _decoder(prim: str) -> Callable[[_Reader], Any]:
    if prim == "null":
        return lambda r: None
    if prim == "boolean":
        return lambda r: r.read(1) != b"\x00"
    if prim in ("int", "long"):
        return _Reader.read_long
    if prim == "float":
        return lambda r: struct.unpack("<f", r.read(4))[0]
    if prim == "double":
        return lambda r: struct.unpack("<d", r.read(8))[0]
    if prim == "string":
        return lambda r: r.read_bytes().decode("utf-8")
    if prim == "bytes":
        return _Reader.read_bytes
    raise HyperspaceException(f"avro: unsupported type {prim!r}")


# ---------------------------------------------------------------------------
# Reading.
# ---------------------------------------------------------------------------

def _read_header(r: _Reader, path: str) -> Tuple[Dict[str, bytes], bytes]:
    if r.read(4) != _MAGIC:
        raise HyperspaceException(f"avro: bad magic in {path}")
    meta: Dict[str, bytes] = {}
    while True:
        n = r.read_long()
        if n == 0:
            break
        if n < 0:  # block with explicit byte size
            r.read_long()
            n = -n
        for _ in range(n):
            key = r.read_bytes().decode("utf-8")
            meta[key] = r.read_bytes()
    return meta, r.read(16)


def read_avro_schema(path: str) -> pa.Schema:
    """Arrow schema from the OCF header only (no row decoding)."""
    with open(path, "rb") as fh:
        head = fh.read(65536)  # headers are tiny; schema JSON fits easily
    meta, _ = _read_header(_Reader(head), path)
    if "avro.schema" not in meta:
        raise HyperspaceException(f"avro: no schema in {path}")
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    if schema.get("type") != "record":
        raise HyperspaceException("avro: top-level schema must be a record")
    fields = []
    for f in schema.get("fields", []):
        prim, null_branch, logical = _field_plan(f["type"])
        fields.append(pa.field(f["name"], _arrow_type(prim, logical),
                               nullable=null_branch is not None))
    return pa.schema(fields)


def read_avro(path: str,
              columns: Optional[List[str]] = None) -> pa.Table:
    """Read one OCF file into an arrow table (optionally projecting)."""
    with open(path, "rb") as fh:
        data = fh.read()
    r = _Reader(data)
    meta, sync = _read_header(r, path)
    if "avro.schema" not in meta:
        raise HyperspaceException(f"avro: no schema in {path}")
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    if codec not in ("null", "deflate"):
        raise HyperspaceException(f"avro: unsupported codec {codec!r}")
    if schema.get("type") != "record":
        raise HyperspaceException("avro: top-level schema must be a record")
    fields = schema.get("fields", [])
    plans = [(f["name"], *_field_plan(f["type"])) for f in fields]

    from .. import native as hst_native

    native_plans = [(prim, nb) for _, prim, nb, _ in plans]
    use_native = True
    native_chunks: List[Tuple[int, List]] = []  # (row count, field pieces)
    cells: Dict[str, List[Any]] = {name: [] for name, *_ in plans}
    decoders = [(name, _decoder(prim), null_branch)
                for name, prim, null_branch, _ in plans]
    while not r.at_end():
        count = r.read_long()
        size = r.read_long()
        block = r.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        if count == 0:
            # Zero-object blocks are legal (writers emit them on flush);
            # nothing to decode, and they must NOT flip the native path
            # off — later rows would land in `cells` and be dropped by
            # the native-chunks assembly.
            if r.read(16) != sync:
                raise HyperspaceException(
                    f"avro: sync marker mismatch in {path}")
            continue
        decoded = None
        if use_native:
            # One C++ pass per block (native/hst_native.cpp); falls back to
            # the Python row loop only when no compiler is available.
            try:
                decoded = hst_native.avro_decode_block(
                    block, count, native_plans)
            except ValueError as e:
                raise HyperspaceException(f"avro: {e} in {path}")
            if decoded is None:
                use_native = False
        if decoded is not None:
            native_chunks.append((count, decoded))
        else:
            br = _Reader(block)
            for _ in range(count):
                for name, dec, null_branch in decoders:
                    if null_branch is not None:
                        branch = br.read_long()
                        cells[name].append(
                            None if branch == null_branch else dec(br))
                    else:
                        cells[name].append(dec(br))
        if r.read(16) != sync:
            raise HyperspaceException(f"avro: sync marker mismatch in {path}")

    arrays = []
    names = []
    for fi, (name, prim, null_branch, logical) in enumerate(plans):
        if columns is not None and name not in columns:
            continue
        if native_chunks:
            arr = _assemble_native(native_chunks, fi, prim, null_branch,
                                   logical)
        else:
            at = _arrow_type(prim, logical)
            vals = cells[name]
            if logical == "date":
                arr = pa.array(
                    np.array([v if v is not None else 0 for v in vals],
                             dtype="int32"),
                    type=pa.int32(),
                    mask=np.array([v is None for v in vals], dtype=bool)
                    if null_branch is not None else None).cast(pa.date32())
            else:
                arr = pa.array(vals, type=at)
        arrays.append(arr)
        names.append(name)
    if columns is not None:
        missing = [c for c in columns if c not in names]
        if missing:
            raise HyperspaceException(
                f"avro: columns {missing} not in {path}")
        order = {n: i for i, n in enumerate(names)}
        arrays = [arrays[order[c]] for c in columns]
        names = list(columns)
    return pa.table(dict(zip(names, arrays)))


def _assemble_native(native_chunks: List[Tuple[int, List]], fi: int,
                     prim: str, null_branch: Optional[int],
                     logical: Optional[str]) -> pa.Array:
    """Arrow array for field ``fi`` from the per-block native decode
    results (per-block pa arrays concatenated — zero Python per row)."""
    parts = []
    nullable = null_branch is not None
    for count, fields in native_chunks:
        piece = fields[fi]
        if piece[0] == "s":
            _, offsets, data, valid = piece
            at = pa.utf8() if prim == "string" else pa.binary()
            validity_buf = None
            null_count = 0
            if nullable:
                null_count = int(count - valid.sum())
                if null_count:
                    validity_buf = pa.py_buffer(np.packbits(
                        valid.astype(bool), bitorder="little").tobytes())
            arr = pa.Array.from_buffers(
                at, count,
                [validity_buf, pa.py_buffer(offsets.tobytes()),
                 pa.py_buffer(data)], null_count)
            if prim == "string":
                # from_buffers does not validate UTF-8; the Python decoder
                # raises on invalid bytes, so the native path must too.
                try:
                    arr.validate(full=True)
                except pa.lib.ArrowInvalid as e:
                    raise HyperspaceException(f"avro: invalid utf-8: {e}")
            parts.append(arr)
            continue
        kind, vals, valid = piece
        mask = (valid == 0) if nullable else None
        if prim == "null":
            parts.append(pa.nulls(count))
        elif logical == "date":
            parts.append(pa.array(vals.astype(np.int32), type=pa.int32(),
                                  mask=mask).cast(pa.date32()))
        elif prim == "boolean":
            parts.append(pa.array(vals.astype(bool), mask=mask))
        elif prim == "int":
            parts.append(pa.array(vals.astype(np.int32), mask=mask))
        elif prim == "long":
            parts.append(pa.array(vals, mask=mask))
        elif prim == "float":
            parts.append(pa.array(vals.astype(np.float32), mask=mask))
        else:  # double
            parts.append(pa.array(vals, mask=mask))
    return pa.concat_arrays(parts) if len(parts) > 1 else parts[0]


# ---------------------------------------------------------------------------
# Writing (null or deflate codec; blocks of block_rows rows).
# ---------------------------------------------------------------------------

_WRITE_PLAN = {
    pa.types.is_boolean: ("boolean", lambda v: b"\x01" if v else b"\x00"),
    pa.types.is_int32: ("int", _encode_long),
    pa.types.is_int64: ("long", _encode_long),
    pa.types.is_float32: ("float", lambda v: struct.pack("<f", v)),
    pa.types.is_float64: ("double", lambda v: struct.pack("<d", v)),
    pa.types.is_string: ("string", lambda v: _encode_bytes(v.encode("utf-8"))),
    pa.types.is_binary: ("bytes", _encode_bytes),
}


def _write_plan_for(t: pa.DataType):
    if pa.types.is_date32(t):
        return ({"type": "int", "logicalType": "date"},
                lambda v: _encode_long((v - _EPOCH).days))
    for pred, plan in _WRITE_PLAN.items():
        if pred(t):
            return plan
    raise HyperspaceException(f"avro: cannot write arrow type {t}")


def write_avro(table: pa.Table, path: str, codec: str = "null",
               block_rows: int = 65536) -> None:
    """Write an arrow table as an OCF file. ``codec``: "null" | "deflate"
    (raw zlib per block, the spec's deflate). Rows are split into blocks
    of ``block_rows`` so readers can stream and deflate compresses in
    bounded windows."""
    if codec not in ("null", "deflate"):
        raise HyperspaceException(f"avro: unsupported codec {codec!r}")
    if block_rows < 1:
        raise HyperspaceException(
            f"avro: block_rows must be >= 1, got {block_rows}")
    fields = []
    encoders = []
    for f in table.schema:
        avro_t, enc = _write_plan_for(f.type)
        nullable = f.nullable
        fields.append({"name": f.name,
                       "type": ["null", avro_t] if nullable else avro_t})
        encoders.append((f.name, enc, nullable))
    schema = {"type": "record", "name": "Root", "fields": fields}
    sync = b"hyperspace_sync!"  # fixed 16-byte marker
    cols = {name: table.column(name).to_pylist() for name, _, _ in encoders}

    def encode_block(start: int, count: int) -> bytes:
        body = io.BytesIO()
        for i in range(start, start + count):
            for name, enc, nullable in encoders:
                v = cols[name][i]
                if nullable:
                    if v is None:
                        body.write(_encode_long(0))
                        continue
                    body.write(_encode_long(1))
                elif v is None:
                    raise HyperspaceException(
                        f"avro: null in non-nullable column {name}")
                body.write(enc(v))
        return body.getvalue()

    with open(path, "wb") as fh:
        fh.write(_MAGIC)
        fh.write(_encode_long(2))
        fh.write(_encode_bytes(b"avro.schema"))
        fh.write(_encode_bytes(json.dumps(schema).encode("utf-8")))
        fh.write(_encode_bytes(b"avro.codec"))
        fh.write(_encode_bytes(codec.encode("utf-8")))
        fh.write(_encode_long(0))
        fh.write(sync)
        for start in range(0, table.num_rows, block_rows):
            count = min(block_rows, table.num_rows - start)
            block = encode_block(start, count)
            if codec == "deflate":
                block = zlib.compress(block)[2:-4]  # raw deflate
            fh.write(_encode_long(count))
            fh.write(_encode_long(len(block)))
            fh.write(block)
            fh.write(sync)
