"""Column-name resolution (parity: util/ResolverUtils.scala:44-162).

Resolves user-provided column names against a schema case-insensitively (or
sensitively, per conf). Nested fields are supported natively: schemas flatten
struct leaves into dotted names at the IO boundary (schema.Schema.from_arrow),
so ``a.b.c`` resolves like any flat name. The reference instead rewrites
nested fields to prefixed flat columns (``__hs_nested.a.b.c``,
util/ResolverUtils.scala:112-162) because Catalyst attribute names cannot
contain dots — a constraint our engine does not have; the prefix constant is
kept for readers of the reference's on-disk metadata.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..exceptions import HyperspaceException

NESTED_FIELD_PREFIX = "__hs_nested."


def is_nested(name: str) -> bool:
    """A dotted name denotes a flattened struct leaf."""
    return "." in name


def resolve(available: Sequence[str], requested: str,
            case_sensitive: bool = False) -> Optional[str]:
    """Resolve one name; returns the schema's spelling or None."""
    if case_sensitive:
        return requested if requested in available else None
    matches = [a for a in available if a.lower() == requested.lower()]
    if len(matches) > 1:
        raise HyperspaceException(
            f"Ambiguous column '{requested}' matches {matches}")
    return matches[0] if matches else None


def resolve_all(available: Sequence[str], requested: Sequence[str],
                case_sensitive: bool = False) -> List[str]:
    """Resolve all names or raise naming the first failure."""
    out = []
    for r in requested:
        resolved = resolve(available, r, case_sensitive)
        if resolved is None:
            raise HyperspaceException(
                f"Column '{r}' could not be resolved; available: {list(available)}")
        out.append(resolved)
    return out
