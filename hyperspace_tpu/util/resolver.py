"""Column-name resolution (parity: util/ResolverUtils.scala:44-162).

Resolves user-provided column names against a schema case-insensitively (or
sensitively, per conf). Nested-field flattening (``a.b.c`` →
``__hs_nested.a.b.c``) is part of the reference contract; our engine's
schemas are flat, so the prefix constant exists but nested inputs are
rejected explicitly rather than mis-resolved.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..exceptions import HyperspaceException

NESTED_FIELD_PREFIX = "__hs_nested."


def resolve(available: Sequence[str], requested: str,
            case_sensitive: bool = False) -> Optional[str]:
    """Resolve one name; returns the schema's spelling or None."""
    if "." in requested:
        raise HyperspaceException(
            f"Nested column '{requested}' is not supported yet "
            f"(flat schemas only; reserved prefix {NESTED_FIELD_PREFIX!r})")
    if case_sensitive:
        return requested if requested in available else None
    matches = [a for a in available if a.lower() == requested.lower()]
    if len(matches) > 1:
        raise HyperspaceException(
            f"Ambiguous column '{requested}' matches {matches}")
    return matches[0] if matches else None


def resolve_all(available: Sequence[str], requested: Sequence[str],
                case_sensitive: bool = False) -> List[str]:
    """Resolve all names or raise naming the first failure."""
    out = []
    for r in requested:
        resolved = resolve(available, r, case_sensitive)
        if resolved is None:
            raise HyperspaceException(
                f"Column '{r}' could not be resolved; available: {list(available)}")
        out.append(resolved)
    return out
