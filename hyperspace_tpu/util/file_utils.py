"""Local/remote filesystem helpers (parity: util/FileUtils.scala, util/PathUtils.scala).

All index data + metadata live on an HDFS-compatible filesystem in the
reference; here the TPU-VM host filesystem plays that role. Writes that must
be crash-consistent go through temp-file + atomic rename.
"""

from __future__ import annotations

import os
import shutil
import uuid
from typing import List


def write_contents(path: str, contents: str) -> None:
    """Overwrite ``path`` with ``contents`` (non-atomic; see atomic_write)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(contents)


def atomic_create(path: str, contents: str) -> bool:
    """Create ``path`` with ``contents`` iff it does not already exist.

    Optimistic concurrency: write to a unique temp file in the same directory
    then ``link``/rename it into place; returns False if the destination
    already exists (reference: IndexLogManager.writeLog, temp + rename that
    fails on existing destination).
    """
    directory = os.path.dirname(path)
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f".tmp-{uuid.uuid4().hex}")
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(contents)
        f.flush()
        os.fsync(f.fileno())
    try:
        # os.link fails with EEXIST if path exists: atomic create-if-absent.
        os.link(tmp, path)
        return True
    except FileExistsError:
        return False
    finally:
        os.unlink(tmp)


def atomic_write_bytes(path: str, contents: bytes,
                       tmp_prefix: str = ".tmp-") -> None:
    """Binary :func:`atomic_overwrite` (artifact usage sidecar):
    atomically replace ``path`` with ``contents`` via fsync'd temp +
    rename. ``tmp_prefix`` names the temp so a crashed writer's
    leftover is recognizable to the owning store's vacuum."""
    directory = os.path.dirname(path)
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f"{tmp_prefix}{uuid.uuid4().hex}")
    with open(tmp, "wb") as f:
        f.write(contents)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_overwrite(path: str, contents: str) -> None:
    """Atomically replace ``path`` with ``contents`` (for latestStable)."""
    directory = os.path.dirname(path)
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f".tmp-{uuid.uuid4().hex}")
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(contents)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_contents(path: str) -> str:
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def _data_store_for(path: str):
    """The registered DataStore for scheme-qualified paths (None for
    local). Lazy import: data_store sits above this module."""
    if "://" not in path:
        return None
    from ..index import data_store
    return data_store.store_for_path(path)


def delete_recursively(path: str) -> None:
    store = _data_store_for(path)
    if store is not None:
        store.delete_recursively(path)
        return
    if os.path.isdir(path):
        shutil.rmtree(path)
    elif os.path.exists(path):
        os.unlink(path)


def list_leaf_files(path: str) -> List[str]:
    """Recursively list all regular files under ``path`` (sorted, full paths).

    Hidden files/dirs (leading '.' or '_') are excluded, matching Spark's
    data-path filter (PathUtils.DataPathFilter), except that '_hyperspace_log'
    style metadata never sits under data dirs anyway.
    """
    store = _data_store_for(path)
    if store is not None:
        return store.list_leaf_files(path)
    out: List[str] = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if not _is_hidden(d))
        for f in sorted(files):
            if not _is_hidden(f):
                out.append(os.path.join(root, f))
    return out


def _is_hidden(name: str) -> bool:
    return name.startswith(".") or name.startswith("_")


def file_info_triple(path: str) -> tuple:
    """(full_path, size, mtime_ms) for a file, the signature triple."""
    store = _data_store_for(path)
    if store is not None:
        return store.file_info(path)
    st = os.stat(path)
    return (os.path.abspath(path), st.st_size, int(st.st_mtime * 1000))


def is_dir(path: str) -> bool:
    """Directory/prefix existence across local FS and data stores."""
    store = _data_store_for(path)
    if store is not None:
        return store.is_dir(path)
    return os.path.isdir(path)


def list_dir(path: str) -> List[str]:
    """Names directly under ``path`` across local FS and data stores."""
    store = _data_store_for(path)
    if store is not None:
        return store.list_dir(path)
    if not os.path.isdir(path):
        return []
    return sorted(os.listdir(path))


def makedirs(path: str) -> None:
    """mkdir -p across local FS and data stores (a no-op marker on flat
    object stores)."""
    store = _data_store_for(path)
    if store is not None:
        store.makedirs(path)
        return
    os.makedirs(path, exist_ok=True)
