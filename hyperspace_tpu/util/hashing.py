"""Hashing helpers (parity: util/HashingUtils.scala — md5-based fingerprints)."""

from __future__ import annotations

import hashlib
from typing import Any


def md5_hex(value: Any) -> str:
    """md5 hex digest of ``str(value)`` (reference: HashingUtils.md5Hex)."""
    return hashlib.md5(str(value).encode("utf-8")).hexdigest()
