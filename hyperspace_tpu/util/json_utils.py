"""JSON (de)serialization for the metadata model.

Parity: util/JsonUtils.scala. The reference uses Jackson with a custom Scala
module; here every metadata class implements ``to_json_dict`` /
``from_json_dict`` and this module handles the envelope.
"""

from __future__ import annotations

import json
from typing import Any


def to_json(obj: Any, indent: int = 2) -> str:
    if hasattr(obj, "to_json_dict"):
        obj = obj.to_json_dict()
    return json.dumps(obj, indent=indent, sort_keys=False)


def from_json(text: str) -> Any:
    return json.loads(text)
