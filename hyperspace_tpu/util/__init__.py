from . import file_utils, hashing, json_utils  # noqa: F401
