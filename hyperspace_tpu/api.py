"""User API facade.

Parity reference: Hyperspace.scala:26-196 (createIndex/deleteIndex/
restoreIndex/vacuumIndex/refreshIndex/optimizeIndex/cancel/indexes/index/
explain) and IndexConfig.scala. Per-session context (manager instances) is
held on the facade, mirroring HyperspaceContext (Hyperspace.scala:169-196).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .exceptions import HyperspaceException
from .index.constants import IndexConstants


@dataclass(frozen=True)
class IndexConfig:
    """Covering-index specification (parity: IndexConfig.scala)."""

    index_name: str
    indexed_columns: List[str]
    included_columns: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.index_name:
            raise HyperspaceException("Index name cannot be empty")
        if not self.indexed_columns:
            raise HyperspaceException("Indexed columns cannot be empty")
        lowered = [c.lower() for c in
                   list(self.indexed_columns) + list(self.included_columns)]
        if len(set(lowered)) != len(lowered):
            raise HyperspaceException(
                "Duplicate columns across indexed/included lists")


@dataclass(frozen=True)
class SketchSpec:
    """One data-skipping sketch over one column (capability of later
    reference versions; see SURVEY.md version note and ops/sketches.py)."""

    kind: str
    column: str

    def properties(self) -> dict:
        return {}


@dataclass(frozen=True)
class MinMaxSketch(SketchSpec):
    kind: str = field(default="MinMax", init=False)

    def __init__(self, column: str):
        object.__setattr__(self, "column", column)
        object.__setattr__(self, "kind", "MinMax")


@dataclass(frozen=True)
class BloomFilterSketch(SketchSpec):
    """Bloom membership sketch; sized from (expected_items, fpp)."""

    kind: str = field(default="BloomFilter", init=False)
    fpp: float = 0.01
    expected_items: int = 100_000

    def __init__(self, column: str, fpp: float = 0.01,
                 expected_items: int = 100_000):
        object.__setattr__(self, "column", column)
        object.__setattr__(self, "kind", "BloomFilter")
        object.__setattr__(self, "fpp", fpp)
        object.__setattr__(self, "expected_items", expected_items)

    def properties(self) -> dict:
        from .ops.sketches import bloom_parameters
        num_bits, num_hashes = bloom_parameters(self.expected_items, self.fpp)
        return {"numBits": str(num_bits), "numHashes": str(num_hashes),
                "fpp": str(self.fpp),
                "expectedItems": str(self.expected_items)}


@dataclass(frozen=True)
class ValueListSketch(SketchSpec):
    """Exact distinct-values sketch for low-cardinality columns: equality
    and IN predicates prune a file unless the literal is IN its stored
    value list (no false positives, unlike Bloom). Files whose cardinality
    exceeds ``max_values`` store no list and are always kept."""

    kind: str = field(default="ValueList", init=False)
    max_values: int = 256

    def __init__(self, column: str, max_values: int = 256):
        object.__setattr__(self, "column", column)
        object.__setattr__(self, "kind", "ValueList")
        object.__setattr__(self, "max_values", int(max_values))

    def properties(self) -> dict:
        return {"maxValues": str(self.max_values)}


@dataclass(frozen=True)
class DataSkippingIndexConfig:
    """Data-skipping index specification: per-source-file sketches."""

    index_name: str
    sketches: List[SketchSpec]

    def __post_init__(self):
        if not self.index_name:
            raise HyperspaceException("Index name cannot be empty")
        if not self.sketches:
            raise HyperspaceException("At least one sketch is required")
        seen = set()
        for s in self.sketches:
            key = (s.kind, s.column.lower())
            if key in seen:
                raise HyperspaceException(
                    f"Duplicate sketch {s.kind} on column {s.column}")
            seen.add(key)


class Hyperspace:
    def __init__(self, session):
        self.session = session
        self.index_manager = session.index_collection_manager

    # ------------------------------------------------------------------
    # CRUD.
    # ------------------------------------------------------------------

    def create_index(self, df, index_config: IndexConfig) -> None:
        self.index_manager.create(df, index_config)

    def delete_index(self, index_name: str) -> None:
        self.index_manager.delete(index_name)

    def restore_index(self, index_name: str) -> None:
        self.index_manager.restore(index_name)

    def vacuum_index(self, index_name: str) -> None:
        self.index_manager.vacuum(index_name)

    def refresh_index(self, index_name: str,
                      mode: str = IndexConstants.REFRESH_MODE_FULL) -> None:
        self.index_manager.refresh(index_name, mode)

    def optimize_index(self, index_name: str,
                       mode: str = IndexConstants.OPTIMIZE_MODE_QUICK) -> None:
        self.index_manager.optimize(index_name, mode)

    def cancel(self, index_name: str) -> None:
        self.index_manager.cancel(index_name)

    def recover(self, index_names=None) -> dict:
        """Crash recovery sweep (robustness/recovery.py): roll every
        index whose latest op-log entry is transient — another process
        died mid create/refresh/optimize/vacuum — back to its last
        stable state, and vacuum data version directories no committed
        entry references (the dead action's partial output). A healthy
        lake is a no-op. OPERATOR ACTION: a transient entry is
        indistinguishable from a LIVE in-flight action, so run this only
        when no other process is mutating the lake (cancelling a live
        action and vacuuming its half-written version is exactly what
        this does to a wreck — and would do to a healthy writer).
        Returns the summary dict ({scanned, cancelled, vacuumed,
        errors})."""
        from .robustness.recovery import recover_indexes
        summary = recover_indexes(self.session, names=index_names)
        # Recovered indexes changed state out from under the caching
        # manager: drop its entry cache so listings see the rollback.
        self.index_manager.clear_cache()
        # Artifact store sweep (r20): a process killed mid-publication
        # leaves only a .tmp- file (never a torn blob — publication is
        # tmp+link); the vacuum clears those plus stale-runtime and
        # corrupt-header blobs. No-op dict when artifacts are off.
        summary["artifacts"] = self._artifact_vacuum()
        return summary

    # ------------------------------------------------------------------
    # Streaming ingestion (streaming/): append/commit + compaction.
    # ------------------------------------------------------------------

    def append(self, table_path: str, batch,
               block: bool = False) -> dict:
        """Stage one record batch (pyarrow Table/RecordBatch, pandas
        DataFrame, or dict of columns) for the parquet table directory
        ``table_path``. The batch is written to a hidden staging file
        (invisible to every scan) and — while its rows are hot on
        device — sketched and bucket-routed into a prebuilt delta for
        each ACTIVE index over the table, so ``commit()`` is pure
        metadata + renames. ``block=True`` parks on a full staging
        budget (bounded by ``backpressure.timeoutMs``) instead of
        raising — the continuous-source posture. Returns a summary
        dict."""
        from .streaming.ingest import append as _append
        return _append(self.session, table_path, batch, block=block)

    def commit(self, table_path: str) -> dict:
        """Publish every staged batch for ``table_path`` atomically
        through the op-log protocol (put-if-absent decides races,
        crash-safe via ``recover()``'s undo/redo sweep), landing the
        prebuilt index deltas in the same commit — covering indexes and
        skipping sketches are fresh with no refresh pass. Standing
        queries (``serving_frontend().subscribe``) re-fire. Returns a
        summary dict."""
        from .streaming.ingest import commit as _commit
        return _commit(self.session, table_path)

    def compact(self, names=None) -> dict:
        """Fold each op-log's superseded entries into one checkpoint
        entry and vacuum unreferenced index data versions
        (streaming/compaction.py) — the maintenance action that keeps a
        long-lived append workload's logs (and query-time log reads)
        bounded. Queries planned after the compaction answer
        byte-identically, and ``recover()`` behavior is unchanged.
        OPERATOR ACTION like ``recover``/``vacuumIndex``: the version
        vacuum deletes bytes a reader mid-scan on a stale entry could
        still need — run it in a quiet window. Returns a summary
        dict."""
        from .streaming.compaction import compact as _compact
        summary = _compact(self.session, names)
        # The artifact store rides the same maintenance action: vacuum
        # unreferenced/stale blobs and re-apply the byte budget.
        summary["artifacts"] = self._artifact_vacuum()
        return summary

    def _artifact_vacuum(self) -> dict:
        """Shared recover()/compact() seam into the artifact store's
        vacuum — maintenance must survive an artifacts-layer failure."""
        try:
            from .artifacts.manager import vacuum as _artifact_vacuum
            return _artifact_vacuum(self.session)
        except Exception:
            return {"enabled": False}

    def tail_directory(self, watch_dir: str, table_path: str,
                       name=None):
        """Start a continuous source (streaming/sources.py): a daemon
        tailing ``watch_dir`` for new ``*.parquet`` drops (atomic
        renames by the producer) and appending/committing them into
        ``table_path`` itself, with blocking backpressure and
        admission-aware pausing. Returns the running source — call
        ``.stop()`` to drain and halt it."""
        from .streaming.sources import tail_directory as _tail
        return _tail(self.session, watch_dir, table_path, name=name)

    def tail_log(self, log_path: str, table_path: str, name=None):
        """Start a continuous source tailing the JSONL log at
        ``log_path`` by byte offset (complete lines only), appending
        each poll's records as one batch into ``table_path``. Returns
        the running source — call ``.stop()`` to drain and halt it."""
        from .streaming.sources import tail_log as _tail
        return _tail(self.session, log_path, table_path, name=name)

    def streaming_stats(self) -> dict:
        """Ingestion-tier observability: the process commit queue's
        counters (appends/commits/rows/deltas/subscription fires), the
        group-commit coordinator's wave ledger, plus the op-log lookup
        cache's hit rates."""
        from .streaming.ingest import get_queue
        return get_queue().stats()

    # ------------------------------------------------------------------
    # Compiled-program artifact store (artifacts/).
    # ------------------------------------------------------------------

    def warmup(self) -> dict:
        """Preload persisted AOT executables from the lake's artifact
        store into this process's program caches, hottest first (by the
        persisted usage tallies), within the ``artifacts.preload.maxMs``
        / ``maxBytes`` budgets — so the first query after a cold boot
        dispatches instead of compiling. Explicit counterpart of the
        opt-in automatic preload at session init
        (``artifacts.preload.enabled``). Returns a summary dict
        ({enabled, loaded, skipped, bytes, ms, budget_hit})."""
        try:
            from .artifacts.manager import preload as _preload
            return _preload(self.session)
        except Exception:
            return {"enabled": False, "loaded": 0}

    def artifact_stats(self) -> dict:
        """Artifact-store observability: persistent-store counters
        (hits/misses/corruptions/persists/evictions + resident bytes)
        merged with the manager's warm-cache and preload numbers. The
        same dict backs the ``artifacts`` metrics collector."""
        try:
            from .artifacts.manager import manager_for
            mgr = manager_for(self.session)
            if mgr is None:
                return {"enabled": False}
            out = {"enabled": True}
            out.update(mgr.stats())
            return out
        except Exception:
            return {"enabled": False}

    # ------------------------------------------------------------------
    # Introspection.
    # ------------------------------------------------------------------

    def indexes(self):
        """Summary DataFrame of all indexes (parity: hs.indexes)."""
        return self.index_manager.indexes()

    def index(self, index_name: str):
        """Extended stats for one index (parity: hs.index(name))."""
        import pandas as pd
        from .index.statistics import IndexStatistics
        entry = self.index_manager.get_index(index_name)
        if entry is None:
            raise HyperspaceException(f"Index with name {index_name} could not be found.")
        usage = self.session._index_usage_counts.get(index_name, 0)
        return pd.DataFrame([IndexStatistics.from_entry(
            entry, usage_count=usage).to_extended_row()])

    def explain(self, df, verbose: bool = False, redirect_func=None,
                mode: str = "plaintext") -> str:
        """Explain the rewrite: lockstep plan diff with changed subtrees
        highlighted, rendered per ``mode`` ("plaintext" | "console" |
        "html" — parity: plananalysis/DisplayMode.scala)."""
        from .plananalysis.explain import explain_string
        text = explain_string(self.session, df.plan, verbose=verbose,
                              mode=mode)
        if redirect_func is not None:
            redirect_func(text)
        return text

    def result_cache_stats(self) -> dict:
        """Serving-layer cache observability: result-cache counters
        (hit/miss/admit/evict per tier), the SQL plan-memo counters, and
        the HBM index-table-cache counters (execution/index_cache.py) in
        one dict. All zeros/None while the cache is disabled."""
        from .execution import index_cache
        cache = self.session.result_cache
        out = {
            "result_cache": cache.stats() if cache is not None else None,
            "sql_plan_cache": dict(self.session._sql_plan_stats),
        }
        if index_cache.enabled():
            ic = index_cache.get_cache()
            out["index_table_cache"] = {
                "hits": ic.hits, "misses": ic.misses,
                "resident_bytes": ic.nbytes,
            }
        else:
            out["index_table_cache"] = None
        return out

    def buffer_pool_stats(self) -> dict:
        """Tiered columnar buffer-pool counters
        (execution/buffer_pool.py): per-tier hits, misses, admissions,
        host→device ``transfers`` (loads + promotions — the warm-path
        signal: 0 new transfers on a fully warm repeat), the eviction
        ladder tallies, ``decode_bytes_saved``, and per-namespace probe
        splits. Delegates to the process metrics registry's
        "buffer_pool" collector — every worker's OpenMetrics scrape
        carries the same dict (fleet awareness without cross-process
        byte shipping)."""
        from .execution import buffer_pool
        from .telemetry.metrics import get_registry
        out = get_registry().collect(
            buffer_pool._mn.COLLECTOR_BUFFER_POOL)
        return out if out is not None else buffer_pool.pool_stats()

    def io_stats(self) -> dict:
        """Process-wide parallel-I/O pool counters (parallel/io.py):
        pooled read fan-outs, file tasks, byte estimates, in-worker
        read+decode seconds, consumer wait seconds, prefetch streams,
        and the current pool width. Delegates to the process metrics
        registry's "io" collector (telemetry/metrics.py) — importing
        the pool module registers it."""
        from .parallel import io as pio
        from .telemetry.metrics import get_registry
        out = get_registry().collect("io")
        return out if out is not None else pio.pool_stats()

    def spmd_stats(self) -> dict:
        """Distributed-tier observability (execution/spmd.py over the
        parallel/sharding launcher): dispatch tallies per path, the mesh
        the next dispatch would span, how many mesh programs this process
        compiled, the last program's compiled-HLO collective counts, and
        the capacity-escalation attempts of the most recent dispatch."""
        import jax

        from .execution import spmd
        from .parallel import distributed_build, sharding
        return {
            "enabled": self.session.hs_conf.distributed_enabled(),
            "mesh_devices": spmd._device_count(self.session),
            "platform": jax.devices()[0].platform,
            "query_dispatches": spmd.DISPATCH_COUNT,
            "sort_dispatches": spmd.SORT_DISPATCH_COUNT,
            "build_dispatches": distributed_build.DISPATCH_COUNT,
            "mesh_programs_compiled": sharding.COMPILE_COUNT,
            "last_collectives": spmd.last_collectives(),
            "last_cap_attempts": spmd.LAST_CAP_ATTEMPTS,
            "file_aligned_scan":
                self.session.hs_conf.distributed_mesh_file_aligned_scan(),
        }

    def metrics(self) -> dict:
        """ONE snapshot over every subsystem (telemetry/metrics.py): the
        process registry's counters/gauges, the live histograms (the
        serving frontend feeds ``serving.latency_ms`` — rolling
        p50/p95/p99 + QPS over
        ``hyperspace.tpu.telemetry.serving.latencyWindow``), and every
        named collector — ``io``, ``program_bank``, ``serving`` plus the
        session-scoped ``result_cache`` and ``spmd`` surfaces — so every
        counter previously reachable only through the five per-subsystem
        stats APIs is reachable here."""
        from .parallel import io as pio
        from .serving.program_bank import get_bank
        from .telemetry.metrics import get_registry
        snap = get_registry().snapshot()
        cols = snap["collectors"]
        from .execution import buffer_pool
        cols.setdefault("io", pio.pool_stats())
        cols.setdefault("program_bank", get_bank().stats())
        cols.setdefault("buffer_pool", buffer_pool.pool_stats())
        cols["result_cache"] = self.result_cache_stats()
        cols["spmd"] = self.spmd_stats()
        if "serving" not in cols:
            cols["serving"] = self.serving_stats()
        return snap

    def metrics_delta(self, before: dict, after: Optional[dict] = None
                      ) -> dict:
        """Numeric leaves that CHANGED between two ``metrics()``
        snapshots, as one flat ``{dotted.path: delta}`` dict —
        ``after=None`` snapshots now. The snapshot-vs-snapshot diff
        bench phases and tests used to hand-roll::

            before = hs.metrics()
            ...work...
            assert hs.metrics_delta(before)["counters.trace.sampled"] == 2
        """
        from .telemetry.exposition import delta
        return delta(before, after if after is not None else self.metrics())

    def metrics_text(self) -> str:
        """The whole ``metrics()`` surface as OpenMetrics text
        exposition (telemetry/exposition.py) — counters, gauges,
        histogram quantiles, and every collector's numeric leaves — so
        an external scraper (or a future multi-process router) can read
        every counter without importing the process. Round-trips
        through the strict OpenMetrics parser.

        With a live cluster node every sample carries a
        ``worker="<id>"`` label so two workers' scrapes stay
        distinguishable; single-process output is byte-identical to
        the unlabeled format (``maybe_node`` never STARTS a node — the
        exposition is read-only)."""
        from .cluster.worker import maybe_node
        from .telemetry.exposition import render_text
        node = maybe_node()
        return render_text(self.metrics(),
                           worker=node.worker_id if node else "")

    def fleet_metrics(self) -> dict:
        """Every live cluster worker's metrics snapshot, keyed by
        worker id, plus an ``aggregate`` dict summing the numeric
        leaves fleet-wide — this process reads its own surface
        directly, peers answer over the cluster transport (unreachable
        peers are skipped; their staleness expiry will drop them from
        the roster). With the cluster disabled the result is just this
        process under its default identity."""
        from .cluster import transport
        from .cluster.worker import get_node
        from .telemetry.exposition import flatten
        workers: dict = {}
        node = get_node(self.session)
        if node is None:
            workers["local"] = self.metrics()
        else:
            workers[node.worker_id] = self.metrics()
            timeout_s = \
                self.session.hs_conf.cluster_forward_timeout_ms() / 1000.0
            for peer in node.membership.peers():
                try:
                    response = transport.send_request(
                        peer.host, peer.port, {"op": "metrics"},
                        timeout_s=timeout_s, session=self.session)
                    if response.get("ok"):
                        workers[peer.worker_id] = response["metrics"]
                except Exception:
                    continue  # dead peer: staleness will route around it
        aggregate: dict = {}
        for snap in workers.values():
            for key, value in flatten(snap).items():
                aggregate[key] = aggregate.get(key, 0.0) + value
        return {"workers": workers, "aggregate": aggregate}

    def serve_metrics(self, port: Optional[int] = None) -> int:
        """Start the opt-in localhost HTTP scrape endpoint
        (``GET 127.0.0.1:<port>/metrics`` serves ``metrics_text()``).
        ``port=None`` reads ``hyperspace.tpu.telemetry.export.httpPort``
        (raising while that conf is 0 — off, the default); an EXPLICIT
        ``port=0`` binds an ephemeral port. Returns the bound port;
        idempotent while a server is up. Stop with
        :meth:`stop_serving_metrics`."""
        from .telemetry.exposition import start_http_exporter
        return start_http_exporter(self.session, port)

    def stop_serving_metrics(self) -> None:
        from .telemetry.exposition import stop_http_exporter
        stop_http_exporter()

    def health(self) -> dict:
        """Evaluate this session's SLO objectives
        (``hyperspace.tpu.telemetry.slo.*``) over the sliding window of
        completed queries RIGHT NOW and return the verdict dict
        (``healthy``, per-objective observed/threshold/breached).
        Healthy→breached transitions emit SloBreachEvent — and, with
        ``hyperspace.tpu.adaptive.admission.enabled``, drive the serving
        frontend's shed/degrade admission (adaptive/admission.py)."""
        from .telemetry.slo import health
        return health(self.session)

    def adaptive_builder(self):
        """The process-default budgeted background builder
        (adaptive/builder.py): ``run_once()`` for one explicit
        maintenance pass, ``start()``/``stop()`` for the self-scheduling
        daemon loop. Passes only act inside serving idle windows and
        only while ``hyperspace.tpu.adaptive.builder.enabled`` holds."""
        from .adaptive.builder import get_builder
        return get_builder(self)

    def adaptive_stats(self) -> dict:
        """One dict over the adaptive control plane: the feedback
        correction store's counters, the admission controller's
        breach/shed/degrade tallies, and the background builder's
        ledger (built / retired / maintained / bytes_spent /
        in_progress)."""
        from .adaptive.admission import get_controller
        from .adaptive.builder import get_ledger
        from .adaptive.feedback import get_store
        return {"feedback": get_store().stats(),
                "admission": get_controller().stats(),
                "builder": get_ledger().stats()}

    def dump_flight_recorder(self, path: Optional[str] = None) -> str:
        """The flight recorder's rings — recently retained traces,
        recent events, anomalies, metrics snapshots — as ONE
        Perfetto/chrome://tracing-loadable JSON document
        (telemetry/flight_recorder.py). Writes to ``path`` when given;
        returns the JSON text either way."""
        import json as _json
        from .telemetry.flight_recorder import get_recorder
        text = _json.dumps(get_recorder().dump(), default=str)
        if path:
            with open(path, "w", encoding="utf-8") as f:
                f.write(text)
        return text

    def explain_analyze(self, df) -> str:
        """EXECUTE the query with its trace forced on (the sample coin
        is pinned — the caller asked for this one) and return one
        post-execution report fusing the span timeline (wall + self
        times), estimated-vs-actual join rows with per-step q-error,
        and the query's io/cache/bank/robustness tallies
        (plananalysis/analyze.py)."""
        from .plananalysis.analyze import explain_analyze_string
        return explain_analyze_string(self.session, df.plan)

    def last_trace(self):
        """The span-tree :class:`~.telemetry.trace.Trace` of this
        session's most recent traced query — None until a query runs
        with ``hyperspace.tpu.telemetry.trace.enabled=true``. Export
        with ``.to_chrome_json()`` (chrome://tracing / Perfetto) or
        render via ``telemetry.trace.render_timeline``."""
        return getattr(self.session, "_last_trace", None)

    def serving_frontend(self):
        """The process-default concurrent serving frontend
        (serving/frontend.py), created on first use with this session as
        its governing session. Requires
        ``hyperspace.tpu.serving.enabled=true``."""
        from .serving.frontend import get_frontend
        return get_frontend(self.session)

    def serving_stats(self) -> dict:
        """Serving-tier observability in one dict: the process-default
        frontend's admission/batching counters (None before any frontend
        exists), the cross-session shared result cache, and the
        process-wide compiled-program bank."""
        from .serving import frontend as fe
        from .serving.program_bank import get_bank
        front = fe._DEFAULT
        if front is not None:
            out = front.stats()
            out["frontend"] = True
        else:
            out = {"frontend": None,
                   "program_bank": get_bank().stats()}
        return out

    def clear_result_cache(self) -> None:
        """Drop every cached result (both tiers) and the SQL plan memo.
        Never needed for correctness — invalidation is by key
        construction — but frees the memory immediately."""
        cache = self.session.result_cache
        if cache is not None:
            cache.clear()
        with self.session._sql_plan_lock:
            self.session._sql_plan_cache.clear()

    def why_not(self, df, index_name: Optional[str] = None) -> str:
        """Report why each index was (not) applied to this query plan.

        Built on the whyNot reason tagging of the next-gen rule framework
        (parity: FILTER_REASONS, rules/IndexFilter.scala:41-52 and
        index/IndexLogEntryTags.scala:57-63); reasons are always collected
        here regardless of ``hyperspace.index.filterReason.enabled``.
        """
        from .rules.apply_hyperspace import apply_hyperspace
        from .rules.column_pruning import prune_columns
        from .rules.index_filters import ReasonCollector
        # silent: a diagnostic pass must not emit index-usage telemetry or
        # clobber the reasons of the last real optimize pass.
        ctx = ReasonCollector(enabled=True, silent=True)
        apply_hyperspace(self.session, prune_columns(df.plan), ctx)
        return ctx.format(index_name)

    # ------------------------------------------------------------------
    # Advisor: workload capture → what-if → recommendation (advisor/).
    # ------------------------------------------------------------------

    def recommend(self, top_k: int = 5):
        """Cost-ranked index recommendations from the captured workload
        (enable capture via ``hyperspace.tpu.advisor.capture.enabled``).
        Pure planning: builds nothing, leaves the index log store
        byte-identical. Returns an AdvisorReport (``.recommendations``,
        ``.explain()``)."""
        from .advisor.recommend import recommend
        return recommend(self.session, top_k=top_k)

    def what_if(self, df, configs):
        """Would building ``configs`` (IndexConfig /
        DataSkippingIndexConfig instances) rewrite this query? Injects
        metadata-only hypothetical entries through the rules'
        ``candidates_for`` hooks and re-runs index selection — no index
        data is built and nothing is persisted. Returns a WhatIfOutcome
        (``.rewritten``, ``.predicted_speedup``, ``.explain()``)."""
        from .advisor.whatif import what_if
        return what_if(self.session, df.plan, configs)

    def build_recommendation(self, recommendation) -> None:
        """Materialize one recommendation's configs through the normal
        create path (this one DOES build index data)."""
        from .advisor.recommend import build_recommendation
        build_recommendation(self, recommendation)

    def workload(self):
        """The captured workload log as a pandas DataFrame (empty until
        ``hyperspace.tpu.advisor.capture.enabled`` is set)."""
        import pandas as pd
        from .advisor.workload import log_for
        rows = log_for(self.session).to_rows()
        return pd.DataFrame(rows, columns=[
            "fingerprint", "tables", "latency_s", "appliedIndexes",
            "rulesFired"])

    # CamelCase aliases for drop-in parity with the reference's API.
    createIndex = create_index
    deleteIndex = delete_index
    restoreIndex = restore_index
    vacuumIndex = vacuum_index
    refreshIndex = refresh_index
    optimizeIndex = optimize_index
    whyNot = why_not
    whatIf = what_if
