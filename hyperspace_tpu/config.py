"""Runtime configuration system.

Mirrors the reference's conf-string approach (util/HyperspaceConf.scala:26-118,
util/CacheWithTransform.scala): every knob is a string conf read lazily per
call, so values are runtime-changeable. Expensive derived values (e.g. the
source-provider manager built from a class-name list) go through
CacheWithTransform, which re-derives only when the raw conf string changes;
cheap scalar accessors just re-parse per call.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Generic, Optional, TypeVar

from .adaptive.constants import AdaptiveConstants
from .advisor.constants import AdvisorConstants
from .artifacts.constants import ArtifactConstants
from .cluster.constants import ClusterConstants
from .index.constants import IndexConstants
from .optimizer.constants import OptimizerConstants
from .robustness.constants import RobustnessConstants
from .serving.constants import ServingConstants
from .streaming.constants import StreamingConstants
from .telemetry.constants import TelemetryConstants

T = TypeVar("T")

# Capability probe result, filled on first ask (None = not probed yet).
_SPMD_CAPABLE: Optional[bool] = None


def spmd_capable() -> bool:
    """True when the mesh-partitioned SPMD tier can run on this image:
    jax imports, the Mesh/NamedSharding/PartitionSpec sharding API exists,
    and at least one device is visible. The distributed tier is built
    entirely on ``jax.jit`` + ``NamedSharding`` (parallel/sharding.py), so
    this — and NOT the presence of any per-device mapping primitive — is
    the gating capability. ``distributed_enabled()`` defaults on exactly
    when this passes; an explicit conf setting always overrides."""
    global _SPMD_CAPABLE
    if _SPMD_CAPABLE is None:
        try:
            import jax
            import jax.sharding as _shd
            _SPMD_CAPABLE = (
                hasattr(jax, "jit")
                and all(hasattr(_shd, n) for n in
                        ("Mesh", "NamedSharding", "PartitionSpec"))
                and len(jax.devices()) >= 1)
        except Exception:
            _SPMD_CAPABLE = False
    return bool(_SPMD_CAPABLE)


class Conf:
    """A mutable string-keyed configuration map (the SparkConf analogue)."""

    def __init__(self, initial: Optional[Dict[str, str]] = None):
        self._conf: Dict[str, str] = dict(initial or {})

    def set(self, key: str, value: Any) -> "Conf":
        self._conf[key] = str(value)
        return self

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._conf.get(key, default)

    def unset(self, key: str) -> None:
        self._conf.pop(key, None)

    def contains(self, key: str) -> bool:
        return key in self._conf

    def copy(self) -> "Conf":
        return Conf(dict(self._conf))

    def as_dict(self) -> Dict[str, str]:
        return dict(self._conf)


class CacheWithTransform(Generic[T]):
    """Caches ``transform(raw)`` and re-derives when the raw conf string changes.

    Parity: util/CacheWithTransform.scala:1-45. Thread-safe: holders are
    probed on every execute() of the multi-threaded serving path, and an
    unlocked check-then-transform could build two instances and tear a
    (raw, value) pair.
    """

    def __init__(self, load_func: Callable[[], str], transform: Callable[[str], T]):
        self._load_func = load_func
        self._transform = transform
        self._cached_raw: Optional[str] = None
        self._cached_value: Optional[T] = None
        import threading
        self._lock = threading.Lock()

    def load(self) -> T:
        raw = self._load_func()
        with self._lock:
            if self._cached_raw is None or raw != self._cached_raw:
                self._cached_value = self._transform(raw)
                self._cached_raw = raw
            return self._cached_value  # type: ignore[return-value]


class HyperspaceConf:
    """Typed accessors over a :class:`Conf` (util/HyperspaceConf.scala:26-118)."""

    def __init__(self, conf: Conf):
        self._conf = conf

    @property
    def conf(self) -> Conf:
        return self._conf

    def system_path(self) -> str:
        path = self._conf.get(IndexConstants.INDEX_SYSTEM_PATH)
        if not path:
            raise ValueError(
                f"Config '{IndexConstants.INDEX_SYSTEM_PATH}' is not set; it must point at "
                "the root directory under which indexes are stored.")
        return path

    def num_bucket_count(self) -> int:
        return int(
            self._conf.get(
                IndexConstants.INDEX_NUM_BUCKETS,
                str(IndexConstants.INDEX_NUM_BUCKETS_DEFAULT)))

    def hybrid_scan_enabled(self) -> bool:
        return self._get_bool(
            IndexConstants.INDEX_HYBRID_SCAN_ENABLED,
            IndexConstants.INDEX_HYBRID_SCAN_ENABLED_DEFAULT)

    def hybrid_scan_deleted_ratio_threshold(self) -> float:
        return float(
            self._conf.get(
                IndexConstants.INDEX_HYBRID_SCAN_DELETED_RATIO_THRESHOLD,
                IndexConstants.INDEX_HYBRID_SCAN_DELETED_RATIO_THRESHOLD_DEFAULT))

    def hybrid_scan_appended_ratio_threshold(self) -> float:
        return float(
            self._conf.get(
                IndexConstants.INDEX_HYBRID_SCAN_APPENDED_RATIO_THRESHOLD,
                IndexConstants.INDEX_HYBRID_SCAN_APPENDED_RATIO_THRESHOLD_DEFAULT))

    def use_bucket_spec_for_filter_rule(self) -> bool:
        return self._get_bool(
            IndexConstants.INDEX_FILTER_RULE_USE_BUCKET_SPEC,
            IndexConstants.INDEX_FILTER_RULE_USE_BUCKET_SPEC_DEFAULT)

    def filter_reason_enabled(self) -> bool:
        return self._get_bool(
            IndexConstants.INDEX_FILTER_REASON_ENABLED,
            IndexConstants.INDEX_FILTER_REASON_ENABLED_DEFAULT)

    def score_based_optimizer_enabled(self) -> bool:
        return self._get_bool(
            IndexConstants.SCORE_BASED_OPTIMIZER_ENABLED,
            IndexConstants.SCORE_BASED_OPTIMIZER_ENABLED_DEFAULT)

    def index_lineage_enabled(self) -> bool:
        return self._get_bool(
            IndexConstants.INDEX_LINEAGE_ENABLED,
            IndexConstants.INDEX_LINEAGE_ENABLED_DEFAULT)

    def case_sensitive(self) -> bool:
        return self._get_bool(
            IndexConstants.CASE_SENSITIVE,
            IndexConstants.CASE_SENSITIVE_DEFAULT)

    def optimize_file_size_threshold(self) -> int:
        return int(
            self._conf.get(
                IndexConstants.OPTIMIZE_FILE_SIZE_THRESHOLD,
                str(IndexConstants.OPTIMIZE_FILE_SIZE_THRESHOLD_DEFAULT)))

    def index_row_group_size(self) -> int:
        return int(
            self._conf.get(
                IndexConstants.INDEX_ROW_GROUP_SIZE,
                str(IndexConstants.INDEX_ROW_GROUP_SIZE_DEFAULT)))

    def index_cache_expiry_seconds(self) -> int:
        return int(
            self._conf.get(
                IndexConstants.INDEX_CACHE_EXPIRY_DURATION_SECONDS,
                IndexConstants.INDEX_CACHE_EXPIRY_DURATION_SECONDS_DEFAULT))

    def event_logger_class(self) -> Optional[str]:
        return self._conf.get(IndexConstants.EVENT_LOGGER_CLASS)

    def file_based_source_builders(self) -> str:
        return self._conf.get(
            IndexConstants.FILE_BASED_SOURCE_BUILDERS,
            "hyperspace_tpu.sources.default.DefaultFileBasedSourceBuilder,"
            "hyperspace_tpu.sources.delta.DeltaLakeSourceBuilder,"
            "hyperspace_tpu.sources.iceberg.IcebergSourceBuilder")

    def globbing_patterns(self) -> list:
        raw = self._conf.get(IndexConstants.GLOBBING_PATTERN_KEY, "")
        return [p.strip() for p in raw.split(",") if p.strip()]

    def tpu_execution_enabled(self) -> bool:
        return self._get_bool(
            IndexConstants.TPU_EXECUTION_ENABLED,
            IndexConstants.TPU_EXECUTION_ENABLED_DEFAULT)

    def distributed_enabled(self) -> bool:
        """Distributed (mesh-partitioned) execution. An explicit setting
        always wins; UNSET defaults on exactly when :func:`spmd_capable`
        says the partitioned-jit tier can run on this image — the
        capability probe, not a hardcoded default, decides."""
        v = self._conf.get(IndexConstants.TPU_DISTRIBUTED_ENABLED)
        if v is not None:
            return v.strip().lower() == "true"
        return (IndexConstants.TPU_DISTRIBUTED_ENABLED_DEFAULT == "true"
                and spmd_capable())

    def distributed_mesh_max_devices(self) -> int:
        return int(self._conf.get(
            IndexConstants.TPU_DISTRIBUTED_MESH_MAX_DEVICES,
            IndexConstants.TPU_DISTRIBUTED_MESH_MAX_DEVICES_DEFAULT))

    def distributed_min_stream_rows(self) -> int:
        return int(self._conf.get(
            IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS,
            IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS_DEFAULT))

    def distributed_mesh_file_aligned_scan(self) -> bool:
        return self._get_bool(
            IndexConstants.TPU_DISTRIBUTED_MESH_FILE_ALIGNED_SCAN,
            IndexConstants.TPU_DISTRIBUTED_MESH_FILE_ALIGNED_SCAN_DEFAULT)

    def distributed_single_device(self) -> str:
        v = str(self._conf.get(
            IndexConstants.TPU_DISTRIBUTED_SINGLE_DEVICE,
            IndexConstants.TPU_DISTRIBUTED_SINGLE_DEVICE_DEFAULT)).lower()
        # Accept the sibling boolean flags' spellings; reject garbage
        # loudly instead of silently coercing to "auto".
        v = {"true": "on", "false": "off"}.get(v, v)
        if v not in ("auto", "on", "off"):
            from .exceptions import HyperspaceException
            raise HyperspaceException(
                f"{IndexConstants.TPU_DISTRIBUTED_SINGLE_DEVICE} must be "
                f"auto/on/off (or true/false), got {v!r}")
        return v

    def build_rows_per_shard(self) -> int:
        return int(
            self._conf.get(
                IndexConstants.TPU_BUILD_ROWS_PER_SHARD,
                IndexConstants.TPU_BUILD_ROWS_PER_SHARD_DEFAULT))

    def trace_dir(self) -> Optional[str]:
        return self._conf.get(IndexConstants.TPU_TRACE_DIR)

    def shape_bucketing_enabled(self) -> bool:
        return self._get_bool(
            IndexConstants.TPU_SHAPE_BUCKETING_ENABLED,
            IndexConstants.TPU_SHAPE_BUCKETING_ENABLED_DEFAULT)

    def shape_bucketing_growth_factor(self) -> float:
        return float(self._conf.get(
            IndexConstants.TPU_SHAPE_BUCKETING_GROWTH_FACTOR,
            IndexConstants.TPU_SHAPE_BUCKETING_GROWTH_FACTOR_DEFAULT))

    def shape_bucketing_min_pad(self) -> int:
        return int(self._conf.get(
            IndexConstants.TPU_SHAPE_BUCKETING_MIN_PAD,
            IndexConstants.TPU_SHAPE_BUCKETING_MIN_PAD_DEFAULT))

    def shape_bucketing_max_waste_ratio(self) -> float:
        return float(self._conf.get(
            IndexConstants.TPU_SHAPE_BUCKETING_MAX_WASTE_RATIO,
            IndexConstants.TPU_SHAPE_BUCKETING_MAX_WASTE_RATIO_DEFAULT))

    def shape_bucketing_exact_fallback_rows(self) -> int:
        return int(self._conf.get(
            IndexConstants.TPU_SHAPE_BUCKETING_EXACT_FALLBACK_ROWS,
            IndexConstants.TPU_SHAPE_BUCKETING_EXACT_FALLBACK_ROWS_DEFAULT))

    def fusion_enabled(self) -> bool:
        """Whole-plan fusion (execution/fusion.py): execute maximal
        filter/project/join-probe/aggregate regions as ONE banked XLA
        program. Off restores pure staged (operator-at-a-time)
        execution with byte-identical answers."""
        return self._get_bool(
            IndexConstants.TPU_FUSION_ENABLED,
            IndexConstants.TPU_FUSION_ENABLED_DEFAULT)

    def fusion_min_stages(self) -> int:
        return int(self._conf.get(
            IndexConstants.TPU_FUSION_MIN_STAGES,
            IndexConstants.TPU_FUSION_MIN_STAGES_DEFAULT))

    # ------------------------------------------------------------------
    # Parallel I/O (parallel/io.py): reader pool + prefetch pipelines.
    # ------------------------------------------------------------------

    def io_enabled(self) -> bool:
        return self._get_bool(
            IndexConstants.TPU_IO_ENABLED,
            IndexConstants.TPU_IO_ENABLED_DEFAULT)

    def io_threads(self) -> int:
        """Reader-pool width; 0 = auto (min(16, cpu count)), 1 = fully
        sequential reads (the determinism-baseline setting)."""
        return int(self._conf.get(
            IndexConstants.TPU_IO_THREADS,
            IndexConstants.TPU_IO_THREADS_DEFAULT))

    def io_prefetch_depth(self) -> int:
        return int(self._conf.get(
            IndexConstants.TPU_IO_PREFETCH_DEPTH,
            IndexConstants.TPU_IO_PREFETCH_DEPTH_DEFAULT))

    def io_max_inflight_bytes(self) -> int:
        return int(self._conf.get(
            IndexConstants.TPU_IO_MAX_INFLIGHT_BYTES,
            IndexConstants.TPU_IO_MAX_INFLIGHT_BYTES_DEFAULT))

    # ------------------------------------------------------------------
    # Tiered columnar buffer pool (execution/buffer_pool.py).
    # ------------------------------------------------------------------

    def buffer_pool_enabled(self) -> bool:
        return self._get_bool(
            IndexConstants.TPU_BUFFER_POOL_ENABLED,
            IndexConstants.TPU_BUFFER_POOL_ENABLED_DEFAULT)

    def buffer_pool_device_bytes(self) -> int:
        return int(self._conf.get(
            IndexConstants.TPU_BUFFER_POOL_DEVICE_BYTES,
            IndexConstants.TPU_BUFFER_POOL_DEVICE_BYTES_DEFAULT))

    def buffer_pool_host_bytes(self) -> int:
        return int(self._conf.get(
            IndexConstants.TPU_BUFFER_POOL_HOST_BYTES,
            IndexConstants.TPU_BUFFER_POOL_HOST_BYTES_DEFAULT))

    def buffer_pool_stream_admit_bytes(self) -> int:
        return int(self._conf.get(
            IndexConstants.TPU_BUFFER_POOL_STREAM_ADMIT_BYTES,
            IndexConstants.TPU_BUFFER_POOL_STREAM_ADMIT_BYTES_DEFAULT))

    def max_chunk_rows(self) -> int:
        return int(
            self._conf.get(
                IndexConstants.TPU_MAX_CHUNK_ROWS,
                IndexConstants.TPU_MAX_CHUNK_ROWS_DEFAULT))

    # ------------------------------------------------------------------
    # Serving layer (serving/constants.py). The env-var fallbacks follow
    # the HST_INDEX_CACHE* convention but are resolved HERE and nowhere
    # else — scripts/lint.py rejects os.environ reads in new modules.
    # ------------------------------------------------------------------

    def _serving_get(self, key: str, default: str) -> str:
        v = self._conf.get(key)
        if v is not None:
            return v
        env_key = ServingConstants.ENV_FALLBACKS.get(key)
        if env_key:
            ev = os.environ.get(env_key)
            if ev is not None:
                # Accept the index-cache env spellings for the boolean.
                return {"on": "true", "off": "false"}.get(
                    ev.strip().lower(), ev)
        return default

    def result_cache_enabled(self) -> bool:
        return self._serving_get(
            ServingConstants.RESULT_CACHE_ENABLED,
            ServingConstants.RESULT_CACHE_ENABLED_DEFAULT
        ).strip().lower() == "true"

    def result_cache_device_bytes(self) -> int:
        return int(self._serving_get(
            ServingConstants.RESULT_CACHE_DEVICE_BYTES,
            ServingConstants.RESULT_CACHE_DEVICE_BYTES_DEFAULT))

    def result_cache_host_bytes(self) -> int:
        return int(self._serving_get(
            ServingConstants.RESULT_CACHE_HOST_BYTES,
            ServingConstants.RESULT_CACHE_HOST_BYTES_DEFAULT))

    def result_cache_min_compute_seconds(self) -> float:
        return float(self._serving_get(
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS,
            ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS_DEFAULT))

    def result_cache_min_input_bytes(self) -> int:
        return int(self._serving_get(
            ServingConstants.RESULT_CACHE_MIN_INPUT_BYTES,
            ServingConstants.RESULT_CACHE_MIN_INPUT_BYTES_DEFAULT))

    def result_cache_plan_cache_size(self) -> int:
        return int(self._serving_get(
            ServingConstants.RESULT_CACHE_PLAN_CACHE_SIZE,
            ServingConstants.RESULT_CACHE_PLAN_CACHE_SIZE_DEFAULT))

    def result_cache_spill_dir(self) -> str:
        """Directory of the optional disk-spill tier (host-tier LRU
        victims spill to files there instead of being dropped); empty =
        spill disabled (the pre-spill two-tier behavior)."""
        return self._serving_get(
            ServingConstants.RESULT_CACHE_SPILL_DIR,
            ServingConstants.RESULT_CACHE_SPILL_DIR_DEFAULT).strip()

    def result_cache_spill_bytes(self) -> int:
        return int(self._serving_get(
            ServingConstants.RESULT_CACHE_SPILL_BYTES,
            ServingConstants.RESULT_CACHE_SPILL_BYTES_DEFAULT))

    def result_cache_conf_string(self) -> str:
        """Raw identity of the cache INSTANCE (CacheWithTransform key):
        enabled flag + tier budgets. Admission thresholds are read live
        per query, so tuning them does not drop a warm cache."""
        return "|".join([
            str(self.result_cache_enabled()),
            str(self.result_cache_device_bytes()),
            str(self.result_cache_host_bytes()),
            self.result_cache_spill_dir(),
            str(self.result_cache_spill_bytes()),
        ])

    # ------------------------------------------------------------------
    # Concurrent serving frontend (serving/frontend.py).
    # ------------------------------------------------------------------

    def serving_enabled(self) -> bool:
        return self._get_bool(
            ServingConstants.SERVING_ENABLED,
            ServingConstants.SERVING_ENABLED_DEFAULT)

    def serving_max_concurrency(self) -> int:
        return max(int(self._conf.get(
            ServingConstants.SERVING_MAX_CONCURRENCY,
            ServingConstants.SERVING_MAX_CONCURRENCY_DEFAULT)), 1)

    def serving_queue_depth(self) -> int:
        return max(int(self._conf.get(
            ServingConstants.SERVING_QUEUE_DEPTH,
            ServingConstants.SERVING_QUEUE_DEPTH_DEFAULT)), 1)

    def serving_admission_max_bytes(self) -> int:
        return max(int(self._conf.get(
            ServingConstants.SERVING_ADMISSION_MAX_BYTES,
            ServingConstants.SERVING_ADMISSION_MAX_BYTES_DEFAULT)), 1)

    def serving_batching_enabled(self) -> bool:
        return self._get_bool(
            ServingConstants.SERVING_BATCHING_ENABLED,
            ServingConstants.SERVING_BATCHING_ENABLED_DEFAULT)

    def serving_batching_window(self) -> float:
        return max(float(self._conf.get(
            ServingConstants.SERVING_BATCHING_WINDOW,
            ServingConstants.SERVING_BATCHING_WINDOW_DEFAULT)), 0.0)

    def serving_batching_max_batch(self) -> int:
        return max(int(self._conf.get(
            ServingConstants.SERVING_BATCHING_MAX_BATCH,
            ServingConstants.SERVING_BATCHING_MAX_BATCH_DEFAULT)), 1)

    # ------------------------------------------------------------------
    # Advisor (advisor/constants.py): workload capture + recommendation.
    # ------------------------------------------------------------------

    def advisor_capture_enabled(self) -> bool:
        return self._get_bool(
            AdvisorConstants.CAPTURE_ENABLED,
            AdvisorConstants.CAPTURE_ENABLED_DEFAULT)

    def advisor_capture_max_entries(self) -> int:
        return int(self._conf.get(
            AdvisorConstants.CAPTURE_MAX_ENTRIES,
            AdvisorConstants.CAPTURE_MAX_ENTRIES_DEFAULT))

    def advisor_max_candidates(self) -> int:
        return int(self._conf.get(
            AdvisorConstants.MAX_CANDIDATES,
            AdvisorConstants.MAX_CANDIDATES_DEFAULT))

    def advisor_min_support(self) -> int:
        return int(self._conf.get(
            AdvisorConstants.MIN_SUPPORT,
            AdvisorConstants.MIN_SUPPORT_DEFAULT))

    # ------------------------------------------------------------------
    # Cost-based optimizer (optimizer/constants.py): statistics provider
    # + join reordering.
    # ------------------------------------------------------------------

    def optimizer_stats_enabled(self) -> bool:
        return self._get_bool(
            OptimizerConstants.STATS_ENABLED,
            OptimizerConstants.STATS_ENABLED_DEFAULT)

    def optimizer_stats_sample_rows(self) -> int:
        return int(self._conf.get(
            OptimizerConstants.STATS_SAMPLE_ROWS,
            OptimizerConstants.STATS_SAMPLE_ROWS_DEFAULT))

    def optimizer_stats_cache_entries(self) -> int:
        return int(self._conf.get(
            OptimizerConstants.STATS_CACHE_ENTRIES,
            OptimizerConstants.STATS_CACHE_ENTRIES_DEFAULT))

    def join_reorder_enabled(self) -> bool:
        return self._get_bool(
            OptimizerConstants.JOIN_REORDER_ENABLED,
            OptimizerConstants.JOIN_REORDER_ENABLED_DEFAULT)

    def join_reorder_dp_threshold(self) -> int:
        return int(self._conf.get(
            OptimizerConstants.JOIN_REORDER_DP_THRESHOLD,
            OptimizerConstants.JOIN_REORDER_DP_THRESHOLD_DEFAULT))

    # ------------------------------------------------------------------
    # Telemetry (telemetry/constants.py): tracing, metrics, profiler.
    # ------------------------------------------------------------------

    def telemetry_trace_enabled(self) -> bool:
        return self._get_bool(
            TelemetryConstants.TRACE_ENABLED,
            TelemetryConstants.TRACE_ENABLED_DEFAULT)

    def telemetry_trace_max_spans(self) -> int:
        return int(self._conf.get(
            TelemetryConstants.TRACE_MAX_SPANS,
            TelemetryConstants.TRACE_MAX_SPANS_DEFAULT))

    def telemetry_trace_sample_rate(self) -> float:
        """Head-sampled trace RETENTION probability in [0, 1]; see
        telemetry/constants.py for the provisional-recording contract."""
        return min(max(float(self._conf.get(
            TelemetryConstants.TRACE_SAMPLE_RATE,
            TelemetryConstants.TRACE_SAMPLE_RATE_DEFAULT)), 0.0), 1.0)

    def telemetry_trace_tail_slow_ms(self) -> float:
        return max(float(self._conf.get(
            TelemetryConstants.TRACE_TAIL_SLOW_MS,
            TelemetryConstants.TRACE_TAIL_SLOW_MS_DEFAULT)), 0.0)

    def telemetry_flight_enabled(self) -> bool:
        return self._get_bool(
            TelemetryConstants.FLIGHT_ENABLED,
            TelemetryConstants.FLIGHT_ENABLED_DEFAULT)

    def telemetry_flight_max_traces(self) -> int:
        return max(int(self._conf.get(
            TelemetryConstants.FLIGHT_MAX_TRACES,
            TelemetryConstants.FLIGHT_MAX_TRACES_DEFAULT)), 1)

    def telemetry_slo_enabled(self) -> bool:
        return self._get_bool(
            TelemetryConstants.SLO_ENABLED,
            TelemetryConstants.SLO_ENABLED_DEFAULT)

    def telemetry_slo_p99_ms(self) -> float:
        return max(float(self._conf.get(
            TelemetryConstants.SLO_P99_MS,
            TelemetryConstants.SLO_P99_MS_DEFAULT)), 0.0)

    def telemetry_slo_error_rate(self) -> float:
        return max(float(self._conf.get(
            TelemetryConstants.SLO_ERROR_RATE,
            TelemetryConstants.SLO_ERROR_RATE_DEFAULT)), 0.0)

    def telemetry_slo_degrade_rate(self) -> float:
        return max(float(self._conf.get(
            TelemetryConstants.SLO_DEGRADE_RATE,
            TelemetryConstants.SLO_DEGRADE_RATE_DEFAULT)), 0.0)

    def telemetry_slo_window_s(self) -> float:
        return max(float(self._conf.get(
            TelemetryConstants.SLO_WINDOW_S,
            TelemetryConstants.SLO_WINDOW_S_DEFAULT)), 0.001)

    def telemetry_slo_min_count(self) -> int:
        return max(int(self._conf.get(
            TelemetryConstants.SLO_MIN_COUNT,
            TelemetryConstants.SLO_MIN_COUNT_DEFAULT)), 1)

    def telemetry_export_http_port(self) -> int:
        return max(int(self._conf.get(
            TelemetryConstants.EXPORT_HTTP_PORT,
            TelemetryConstants.EXPORT_HTTP_PORT_DEFAULT)), 0)

    def telemetry_metrics_enabled(self) -> bool:
        return self._get_bool(
            TelemetryConstants.METRICS_ENABLED,
            TelemetryConstants.METRICS_ENABLED_DEFAULT)

    def telemetry_serving_latency_window(self) -> float:
        return max(float(self._conf.get(
            TelemetryConstants.SERVING_LATENCY_WINDOW,
            TelemetryConstants.SERVING_LATENCY_WINDOW_DEFAULT)), 0.001)

    def telemetry_profiler_enabled(self) -> bool:
        return self._get_bool(
            TelemetryConstants.PROFILER_ENABLED,
            TelemetryConstants.PROFILER_ENABLED_DEFAULT)

    def telemetry_profiler_dir(self) -> str:
        return self._conf.get(
            TelemetryConstants.PROFILER_DIR,
            TelemetryConstants.PROFILER_DIR_DEFAULT) or ""

    # ------------------------------------------------------------------
    # Streaming ingestion (streaming/constants.py): append/commit,
    # load-time indexing, compaction, standing queries.
    # ------------------------------------------------------------------

    def streaming_enabled(self) -> bool:
        return self._get_bool(
            StreamingConstants.ENABLED,
            StreamingConstants.ENABLED_DEFAULT)

    def streaming_max_staged_batches(self) -> int:
        return max(int(self._conf.get(
            StreamingConstants.MAX_STAGED_BATCHES,
            StreamingConstants.MAX_STAGED_BATCHES_DEFAULT)), 1)

    def streaming_load_time_indexing(self) -> bool:
        return self._get_bool(
            StreamingConstants.LOAD_TIME_INDEXING,
            StreamingConstants.LOAD_TIME_INDEXING_DEFAULT)

    def streaming_compaction_min_entries(self) -> int:
        return max(int(self._conf.get(
            StreamingConstants.COMPACTION_MIN_ENTRIES,
            StreamingConstants.COMPACTION_MIN_ENTRIES_DEFAULT)), 1)

    def streaming_group_commit_enabled(self) -> bool:
        return self._get_bool(
            StreamingConstants.GROUP_COMMIT_ENABLED,
            StreamingConstants.GROUP_COMMIT_ENABLED_DEFAULT)

    def streaming_group_commit_window_ms(self) -> float:
        return max(float(self._conf.get(
            StreamingConstants.GROUP_COMMIT_WINDOW_MS,
            StreamingConstants.GROUP_COMMIT_WINDOW_MS_DEFAULT)), 0.0)

    def streaming_group_commit_max_wave(self) -> int:
        return max(int(self._conf.get(
            StreamingConstants.GROUP_COMMIT_MAX_WAVE,
            StreamingConstants.GROUP_COMMIT_MAX_WAVE_DEFAULT)), 1)

    def streaming_source_poll_ms(self) -> float:
        return max(float(self._conf.get(
            StreamingConstants.SOURCE_POLL_MS,
            StreamingConstants.SOURCE_POLL_MS_DEFAULT)), 1.0)

    def streaming_source_commit_batches(self) -> int:
        return max(int(self._conf.get(
            StreamingConstants.SOURCE_COMMIT_BATCHES,
            StreamingConstants.SOURCE_COMMIT_BATCHES_DEFAULT)), 1)

    def streaming_backpressure_timeout_ms(self) -> float:
        return max(float(self._conf.get(
            StreamingConstants.BACKPRESSURE_TIMEOUT_MS,
            StreamingConstants.BACKPRESSURE_TIMEOUT_MS_DEFAULT)), 0.0)

    def streaming_subscriptions_max(self) -> int:
        return max(int(self._conf.get(
            StreamingConstants.SUBSCRIPTIONS_MAX,
            StreamingConstants.SUBSCRIPTIONS_MAX_DEFAULT)), 1)

    def streaming_subscription_history(self) -> int:
        return max(int(self._conf.get(
            StreamingConstants.SUBSCRIPTION_HISTORY,
            StreamingConstants.SUBSCRIPTION_HISTORY_DEFAULT)), 1)

    # ------------------------------------------------------------------
    # Robustness (robustness/constants.py): fault injection, deadlines,
    # retry, degradation ladders.
    # ------------------------------------------------------------------

    def robustness_fault_specs(self) -> Dict[str, str]:
        """The armed fault points: ``{point name: spec string}`` from
        every ``hyperspace.tpu.robustness.faults.<point>`` key. Empty
        (the default) means disarmed — fault points compile to a hard
        no-op and the per-run arming scope is skipped entirely."""
        prefix = RobustnessConstants.FAULTS_PREFIX + "."
        out: Dict[str, str] = {}
        for k, v in self._conf.as_dict().items():
            if k.startswith(prefix):
                out[k[len(prefix):]] = v
        return out

    def robustness_seed(self) -> int:
        return int(self._conf.get(
            RobustnessConstants.SEED, RobustnessConstants.SEED_DEFAULT))

    def robustness_deadline_ms(self) -> float:
        return max(float(self._conf.get(
            RobustnessConstants.DEADLINE_MS,
            RobustnessConstants.DEADLINE_MS_DEFAULT)), 0.0)

    def robustness_retry_max_attempts(self) -> int:
        return max(int(self._conf.get(
            RobustnessConstants.RETRY_MAX_ATTEMPTS,
            RobustnessConstants.RETRY_MAX_ATTEMPTS_DEFAULT)), 1)

    def robustness_retry_base_ms(self) -> float:
        return max(float(self._conf.get(
            RobustnessConstants.RETRY_BASE_MS,
            RobustnessConstants.RETRY_BASE_MS_DEFAULT)), 0.0)

    def robustness_degrade_enabled(self) -> bool:
        return self._get_bool(
            RobustnessConstants.DEGRADE_ENABLED,
            RobustnessConstants.DEGRADE_ENABLED_DEFAULT)

    # ------------------------------------------------------------------
    # Adaptive control plane (adaptive/constants.py): feedback-corrected
    # planning, mid-query re-planning, background builder, SLO-driven
    # admission.
    # ------------------------------------------------------------------

    def adaptive_enabled(self) -> bool:
        return self._get_bool(
            AdaptiveConstants.ENABLED, AdaptiveConstants.ENABLED_DEFAULT)

    def adaptive_feedback_enabled(self) -> bool:
        return self.adaptive_enabled() and self._get_bool(
            AdaptiveConstants.FEEDBACK_ENABLED,
            AdaptiveConstants.FEEDBACK_ENABLED_DEFAULT)

    def adaptive_feedback_max_entries(self) -> int:
        return max(int(self._conf.get(
            AdaptiveConstants.FEEDBACK_MAX_ENTRIES,
            AdaptiveConstants.FEEDBACK_MAX_ENTRIES_DEFAULT)), 1)

    def adaptive_feedback_alpha(self) -> float:
        return min(max(float(self._conf.get(
            AdaptiveConstants.FEEDBACK_ALPHA,
            AdaptiveConstants.FEEDBACK_ALPHA_DEFAULT)), 0.01), 1.0)

    def adaptive_replan_enabled(self) -> bool:
        return self.adaptive_enabled() and self._get_bool(
            AdaptiveConstants.REPLAN_ENABLED,
            AdaptiveConstants.REPLAN_ENABLED_DEFAULT)

    def adaptive_replan_error_threshold(self) -> float:
        return max(float(self._conf.get(
            AdaptiveConstants.REPLAN_ERROR_THRESHOLD,
            AdaptiveConstants.REPLAN_ERROR_THRESHOLD_DEFAULT)), 1.0)

    def adaptive_builder_enabled(self) -> bool:
        return self.adaptive_enabled() and self._get_bool(
            AdaptiveConstants.BUILDER_ENABLED,
            AdaptiveConstants.BUILDER_ENABLED_DEFAULT)

    def adaptive_builder_max_bytes(self) -> int:
        return max(int(self._conf.get(
            AdaptiveConstants.BUILDER_MAX_BYTES,
            AdaptiveConstants.BUILDER_MAX_BYTES_DEFAULT)), 0)

    def adaptive_builder_idle_ms(self) -> float:
        return max(float(self._conf.get(
            AdaptiveConstants.BUILDER_IDLE_MS,
            AdaptiveConstants.BUILDER_IDLE_MS_DEFAULT)), 0.0)

    def adaptive_builder_retire_min_queries(self) -> int:
        return max(int(self._conf.get(
            AdaptiveConstants.BUILDER_RETIRE_MIN_QUERIES,
            AdaptiveConstants.BUILDER_RETIRE_MIN_QUERIES_DEFAULT)), 1)

    def adaptive_builder_interval_ms(self) -> float:
        return max(float(self._conf.get(
            AdaptiveConstants.BUILDER_INTERVAL_MS,
            AdaptiveConstants.BUILDER_INTERVAL_MS_DEFAULT)), 10.0)

    def adaptive_admission_enabled(self) -> bool:
        return self.adaptive_enabled() and self._get_bool(
            AdaptiveConstants.ADMISSION_ENABLED,
            AdaptiveConstants.ADMISSION_ENABLED_DEFAULT)

    def adaptive_admission_mode(self) -> str:
        mode = (self._conf.get(
            AdaptiveConstants.ADMISSION_MODE,
            AdaptiveConstants.ADMISSION_MODE_DEFAULT) or "").strip().lower()
        return mode if mode in ("shed", "degrade") else "degrade"

    def adaptive_admission_sample_fraction(self) -> float:
        return min(max(float(self._conf.get(
            AdaptiveConstants.ADMISSION_SAMPLE_FRACTION,
            AdaptiveConstants.ADMISSION_SAMPLE_FRACTION_DEFAULT)),
            0.01), 1.0)

    def artifacts_enabled(self) -> bool:
        return self._get_bool(
            ArtifactConstants.ENABLED, ArtifactConstants.ENABLED_DEFAULT)

    def artifacts_dir(self) -> str:
        return (self._conf.get(
            ArtifactConstants.DIR, ArtifactConstants.DIR_DEFAULT)
            or "").strip()

    def artifacts_max_bytes(self) -> int:
        return max(int(self._conf.get(
            ArtifactConstants.MAX_BYTES,
            ArtifactConstants.MAX_BYTES_DEFAULT)), 0)

    def artifacts_preload_enabled(self) -> bool:
        return self.artifacts_enabled() and self._get_bool(
            ArtifactConstants.PRELOAD_ENABLED,
            ArtifactConstants.PRELOAD_ENABLED_DEFAULT)

    def artifacts_preload_max_ms(self) -> float:
        return max(float(self._conf.get(
            ArtifactConstants.PRELOAD_MAX_MS,
            ArtifactConstants.PRELOAD_MAX_MS_DEFAULT)), 0.0)

    def artifacts_preload_max_bytes(self) -> int:
        return max(int(self._conf.get(
            ArtifactConstants.PRELOAD_MAX_BYTES,
            ArtifactConstants.PRELOAD_MAX_BYTES_DEFAULT)), 0)

    def artifacts_usage_flush_ms(self) -> float:
        return max(float(self._conf.get(
            ArtifactConstants.USAGE_FLUSH_MS,
            ArtifactConstants.USAGE_FLUSH_MS_DEFAULT)), 0.0)

    def cluster_enabled(self) -> bool:
        return self._get_bool(
            ClusterConstants.ENABLED, ClusterConstants.ENABLED_DEFAULT)

    def cluster_worker_id(self) -> str:
        return (self._conf.get(
            ClusterConstants.WORKER_ID,
            ClusterConstants.WORKER_ID_DEFAULT) or "").strip()

    def cluster_bind(self) -> str:
        return (self._conf.get(
            ClusterConstants.BIND, ClusterConstants.BIND_DEFAULT)
            or "127.0.0.1").strip()

    def cluster_port(self) -> int:
        return max(int(self._conf.get(
            ClusterConstants.PORT, ClusterConstants.PORT_DEFAULT)), 0)

    def cluster_dir(self) -> str:
        return (self._conf.get(
            ClusterConstants.DIR, ClusterConstants.DIR_DEFAULT)
            or "").strip()

    def cluster_heartbeat_ms(self) -> float:
        return max(float(self._conf.get(
            ClusterConstants.HEARTBEAT_MS,
            ClusterConstants.HEARTBEAT_MS_DEFAULT)), 50.0)

    def cluster_staleness_ms(self) -> float:
        return max(float(self._conf.get(
            ClusterConstants.STALENESS_MS,
            ClusterConstants.STALENESS_MS_DEFAULT)), 100.0)

    def cluster_routing_enabled(self) -> bool:
        return self.cluster_enabled() and self._get_bool(
            ClusterConstants.ROUTING_ENABLED,
            ClusterConstants.ROUTING_ENABLED_DEFAULT)

    def cluster_forward_timeout_ms(self) -> float:
        return max(float(self._conf.get(
            ClusterConstants.FORWARD_TIMEOUT_MS,
            ClusterConstants.FORWARD_TIMEOUT_MS_DEFAULT)), 10.0)

    def cluster_retry_max_attempts(self) -> int:
        return max(int(self._conf.get(
            ClusterConstants.RETRY_MAX_ATTEMPTS,
            ClusterConstants.RETRY_MAX_ATTEMPTS_DEFAULT)), 1)

    def cluster_broadcast_enabled(self) -> bool:
        return self.cluster_enabled() and self._get_bool(
            ClusterConstants.BROADCAST_ENABLED,
            ClusterConstants.BROADCAST_ENABLED_DEFAULT)

    def cluster_vnodes(self) -> int:
        return max(int(self._conf.get(
            ClusterConstants.VNODES, ClusterConstants.VNODES_DEFAULT)), 1)

    def cluster_gather_mode(self) -> str:
        mode = (self._conf.get(
            ClusterConstants.GATHER,
            ClusterConstants.GATHER_DEFAULT) or "").strip().lower()
        return mode if mode in ("auto", "native", "host") else "auto"

    def cluster_gather_timeout_ms(self) -> float:
        return max(float(self._conf.get(
            ClusterConstants.GATHER_TIMEOUT_MS,
            ClusterConstants.GATHER_TIMEOUT_MS_DEFAULT)), 100.0)

    def _get_bool(self, key: str, default: str) -> bool:
        return (self._conf.get(key, default) or "").strip().lower() == "true"
